"""Pipelined learner feed: the PrefetchPipeline contract (ordering, clean
shutdown, error propagation), the update:data ratio gate, and bit-exact
equivalence of the pipelined and synchronous LearnerService paths through the
real shm store (ISSUE: overlap the host data plane with device compute)."""

import threading
import time

import numpy as np
import pytest

from tests.conftest import small_config
from tpu_rl.data.prefetch import PrefetchPipeline, SynchronousFeed, UpdateRatioGate


# ---------------------------------------------------------------- pipeline
@pytest.mark.timeout(60)
def test_prefetch_ordering_and_no_batch_loss():
    """Every fetched batch reaches the consumer, exactly once, in fetch
    order — the no-loss/no-reorder half of the pipeline contract."""
    n = 50
    counter = iter(range(n))

    def fetch():
        return next(counter, None)

    pipe = PrefetchPipeline(fetch, lambda raws: list(raws), chain=1, depth=2)
    got = []
    deadline = time.time() + 30
    while len(got) < n and time.time() < deadline:
        item = pipe.get(timeout=0.05)
        if item is not None:
            got.append(item[0][0])
    pipe.close()
    assert got == list(range(n))
    assert pipe.dispatched == n


@pytest.mark.timeout(60)
def test_prefetch_chain_accumulation():
    """chain=K hands assemble exactly K raws per dispatch, in order."""
    counter = iter(range(12))

    def fetch():
        return next(counter, None)

    pipe = PrefetchPipeline(fetch, lambda raws: list(raws), chain=3, depth=2)
    got = []
    deadline = time.time() + 30
    while len(got) < 4 and time.time() < deadline:
        item = pipe.get(timeout=0.05)
        if item is not None:
            got.append(item[0])
    pipe.close()
    assert got == [[0, 1, 2], [3, 4, 5], [6, 7, 8], [9, 10, 11]]


@pytest.mark.timeout(60)
def test_prefetch_close_joins_blocked_feeder():
    """close() must terminate the feeder even while it is blocked putting
    into a FULL queue (nobody consuming) — the shutdown-deadlock case."""
    def fetch():
        return 1

    pipe = PrefetchPipeline(fetch, lambda raws: raws, chain=1, depth=1)
    deadline = time.time() + 10
    while pipe.qsize() < 1 and time.time() < deadline:
        time.sleep(0.01)
    assert pipe.qsize() == 1  # feeder is now blocked on the next put
    pipe.close(timeout=10)
    assert not pipe._thread.is_alive()


@pytest.mark.timeout(60)
def test_prefetch_external_stop_event():
    """The shared cluster stop event halts the feeder without close()."""
    stop = threading.Event()
    pipe = PrefetchPipeline(
        lambda: 1, lambda raws: raws, chain=1, depth=1, stop_event=stop
    )
    stop.set()
    deadline = time.time() + 10
    while pipe._thread.is_alive() and time.time() < deadline:
        time.sleep(0.01)
    assert not pipe._thread.is_alive()
    pipe.close()


@pytest.mark.timeout(60)
def test_prefetch_feeder_exception_reraises_in_consumer():
    """A feeder-thread exception must surface from get(), not hang."""
    calls = {"n": 0}

    def fetch():
        calls["n"] += 1
        if calls["n"] > 2:
            raise RuntimeError("store exploded")
        return calls["n"]

    pipe = PrefetchPipeline(fetch, lambda raws: raws[0], chain=1, depth=1)
    seen_error = False
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            pipe.get(timeout=0.05)
        except RuntimeError as e:
            assert "store exploded" in str(e)
            seen_error = True
            break
    assert seen_error
    pipe.close()


def test_prefetch_rejects_bad_depth():
    with pytest.raises(ValueError):
        PrefetchPipeline(lambda: None, lambda r: r, depth=0)


# --------------------------------------------------------- synchronous feed
def test_synchronous_feed_accumulates_chain_across_none():
    """A starving store (fetch -> None) must preserve already-accumulated
    chain members; the dispatch completes once the store recovers."""
    seq = iter([10, None, 11, None, None, 12])

    def fetch():
        return next(seq, None)

    feed = SynchronousFeed(fetch, lambda raws: list(raws), chain=3)
    results = []
    for _ in range(6):
        item = feed.get()
        if item is not None:
            results.append(item[0])
    assert results == [[10, 11, 12]]
    feed.close()  # no-op, but part of the interface


# ------------------------------------------------------------- ratio gate
def test_update_ratio_gate_arithmetic():
    gate = UpdateRatioGate(max_ratio=0.5)  # 1 update per 2 transitions
    assert not gate.ready(0)  # no data yet: never update
    assert gate.ready(2)
    gate.note_fetched()
    assert not gate.ready(2)  # 2nd update needs >= 4 transitions
    assert not gate.ready(3)
    assert gate.ready(4)
    gate.note_fetched()
    assert not gate.ready(4)
    assert gate.ready(1000)  # plenty of headroom after a data burst


def test_update_ratio_gate_rejects_nonpositive():
    with pytest.raises(ValueError):
        UpdateRatioGate(0.0)
    with pytest.raises(ValueError):
        UpdateRatioGate(-1.0)


@pytest.mark.timeout(60)
def test_learner_fetch_honors_ratio_gate_with_stubbed_store():
    """LearnerService._make_fetch wires the gate for off-policy configs:
    fetches stall at the ratio cap and resume as transitions arrive —
    verified against a stubbed ReplayStore-shaped object."""
    from tpu_rl.runtime.learner_service import LearnerService

    cfg = small_config(
        algo="SAC", batch_size=4, max_update_data_ratio=0.1,
    )  # 1 update per 10 transitions

    class StubStore:
        def __init__(self):
            self.transitions = 0
            self.samples = 0

        def transitions_received(self):
            return self.transitions

        def sample(self, batch, rng):
            self.samples += 1
            return {"stub": self.samples}

    store = StubStore()
    svc = LearnerService(cfg, handles=None, model_port=0)
    fetch = svc._make_fetch(store, np.random.default_rng(0))

    assert fetch() is None  # no data at all: gate holds
    assert store.samples == 0

    store.transitions = 25  # budget: floor(0.1 * 25) = 2 updates
    assert fetch() == {"stub": 1}
    assert fetch() == {"stub": 2}
    assert fetch() is None  # cap reached; the store was NOT sampled
    assert store.samples == 2

    store.transitions = 30  # 3 updates earned now
    assert fetch() == {"stub": 3}
    assert fetch() is None


@pytest.mark.timeout(60)
def test_learner_fetch_no_gate_when_ratio_unset():
    """max_update_data_ratio=None (default): off-policy fetch free-runs."""
    from tpu_rl.runtime.learner_service import LearnerService

    cfg = small_config(algo="SAC", batch_size=4)

    class StubStore:
        def transitions_received(self):  # pragma: no cover — must not be used
            raise AssertionError("gateless fetch must not poll the odometer")

        def sample(self, batch, rng):
            return {"stub": 1}

    svc = LearnerService(cfg, handles=None, model_port=0)
    fetch = svc._make_fetch(StubStore(), np.random.default_rng(0))
    assert svc._feed_gate is None
    for _ in range(5):
        assert fetch() == {"stub": 1}


# ------------------------------------------------- service-level equivalence
def _run_service_to_checkpoint(tmp_path, tag, port, prefetch, chain=2):
    """Run a LearnerService through the REAL OnPolicyStore shm path on a
    deterministic window stream; return the checkpointed final state."""
    import jax

    from tpu_rl.algos.registry import get_algo
    from tpu_rl.checkpoint import Checkpointer
    from tpu_rl.data.layout import BatchLayout
    from tpu_rl.data.shm_ring import OnPolicyStore, alloc_handles
    from tpu_rl.runtime.learner_service import LearnerService
    from tpu_rl.types import BATCH_FIELDS

    n_updates, B = 4, 4
    cfg = small_config(
        env="CartPole-v1",
        algo="PPO",
        batch_size=B,
        seq_len=5,
        hidden_size=16,
        learner_chain=chain,
        learner_prefetch=prefetch,
        learner_device="cpu",
        result_dir=None,
        model_dir=str(tmp_path / f"models_{tag}"),
        model_save_interval=100,
        loss_log_interval=1000,
    )
    layout = BatchLayout.from_config(cfg)
    handles = alloc_handles(layout, capacity=B)
    store = OnPolicyStore(handles, layout)

    wrng = np.random.default_rng(7)
    windows = []
    for _ in range(n_updates * B):
        w = {}
        for f in BATCH_FIELDS:
            shape = (layout.seq_len, layout.width(f))
            if f == "act":
                w[f] = wrng.integers(0, 2, size=shape).astype(np.float32)
            elif f == "is_fir":
                a = np.zeros(shape, np.float32)
                a[0] = 1.0
                w[f] = a
            elif f == "log_prob":
                w[f] = np.full(shape, -0.7, np.float32)
            else:
                w[f] = wrng.standard_normal(shape).astype(np.float32) * 0.1
        windows.append(w)

    def feed():
        for w in windows:
            while not store.put(w):
                time.sleep(0.001)

    feeder = threading.Thread(target=feed, daemon=True)
    feeder.start()
    svc = LearnerService(
        cfg, handles, model_port=port, stop_event=threading.Event(),
        max_updates=n_updates, seed=0,
    )
    svc.run()
    feeder.join(timeout=30)
    assert not feeder.is_alive()

    spec = get_algo(cfg.algo)
    template = spec.build(cfg, jax.random.key(0))[1]
    got, idx = Checkpointer(
        str(tmp_path / f"models_{tag}"), cfg.algo
    ).restore_latest(template)
    assert idx == n_updates
    return got, svc


@pytest.mark.timeout(300)
def test_pipelined_matches_synchronous_bit_exact(tmp_path):
    """The acceptance bar: learner_prefetch=2 and learner_prefetch=0 produce
    BIT-IDENTICAL final params on the same window stream — the pipeline
    changes timing, never data, order, or the key schedule."""
    import jax

    sync_state, _ = _run_service_to_checkpoint(
        tmp_path, "sync", port=29850, prefetch=0
    )
    pipe_state, pipe_svc = _run_service_to_checkpoint(
        tmp_path, "pipe", port=29851, prefetch=2
    )
    want = jax.tree_util.tree_leaves(sync_state.params)
    have = jax.tree_util.tree_leaves(pipe_state.params)
    assert want and len(want) == len(have)
    for a, b in zip(want, have, strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # The new pipeline instrumentation must have populated its windows.
    scalars = pipe_svc.timer.scalars()
    assert "learner-queue-wait-time-elapsed-mean-sec" in scalars
    assert "learner-batching-time-elapsed-mean-sec" in scalars
    assert "learner-queue-depth-mean" in scalars
    assert scalars["learner-throughput-transition-per-secs"] > 0
