"""Real N-process ``jax.distributed`` tests (SURVEY.md §2.4 scaled-backend
capability): subprocess "hosts" with 2 virtual CPU devices each bring up
the distributed runtime via ``tpu_rl.parallel.multihost.init_multihost`` and
run REAL cross-process collectives — the DP gradient all-reduce, the ring
attention K/V rotation, and the production ``LearnerService._to_batch``
multihost feed — validating ``host_local_batch_to_global``'s
contiguous-rows assumption against single-device oracles, at 2 AND 4
processes (4 = collectives spanning more than one peer hop).
Body: ``tests/multihost_child.py``."""

import os
import subprocess
import sys
import time

import pytest

CHILD = os.path.join(os.path.dirname(__file__), "multihost_child.py")


def _run_children(nprocs: int, port: int) -> None:
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(CHILD))
    procs = [
        subprocess.Popen(
            [sys.executable, CHILD, str(pid), str(port), str(nprocs)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for pid in range(nprocs)
    ]
    deadline = time.time() + 360
    outs: list = [None] * nprocs
    try:
        for i, p in enumerate(procs):
            remaining = max(5.0, deadline - time.time())
            outs[i], _ = p.communicate(timeout=remaining)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        for i, p in enumerate(procs):
            if outs[i] is None:
                try:
                    outs[i], _ = p.communicate(timeout=10)
                except Exception:
                    outs[i] = "<no output>"
        pytest.fail(
            f"{nprocs}-process distributed run timed out\n" + "\n".join(
                f"--- pid {i} ---\n{(outs[i] or '')[-3000:]}"
                for i in range(nprocs)
            )
        )
    for i, p in enumerate(procs):
        assert p.returncode == 0, (
            f"child {i} rc={p.returncode}\n{outs[i][-3000:]}"
        )
        assert "MULTIHOST_CHILD_OK" in outs[i], outs[i][-3000:]


# slow: each spins up a full subprocess pod (jax.distributed + gloo) on
# this one-core box — ~30s/~70s wall. The tier-1 multihost gate is the
# cheaper fused-pod coverage; these run in the slow tier with
# tests/test_colocated_multihost.py.
@pytest.mark.slow
@pytest.mark.timeout(420)
def test_two_process_distributed_runtime():
    _run_children(2, 29950)


@pytest.mark.slow
@pytest.mark.timeout(420)
def test_four_process_distributed_runtime():
    _run_children(4, 29954)
