"""Real 2-process ``jax.distributed`` test (SURVEY.md §2.4 scaled-backend
capability): two subprocess "hosts" with 2 virtual CPU devices each bring up
the distributed runtime via ``tpu_rl.parallel.multihost.init_multihost`` and
run REAL cross-process collectives — the DP gradient all-reduce and the ring
attention K/V rotation — validating ``host_local_batch_to_global``'s
contiguous-rows assumption and the learner's multihost feed against
single-device oracles. Body: ``tests/multihost_child.py``."""

import os
import subprocess
import sys
import time

import pytest

CHILD = os.path.join(os.path.dirname(__file__), "multihost_child.py")


@pytest.mark.timeout(420)
def test_two_process_distributed_runtime():
    port = 29950
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(CHILD))
    procs = [
        subprocess.Popen(
            [sys.executable, CHILD, str(pid), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    deadline = time.time() + 360
    outs = [None, None]
    try:
        for i, p in enumerate(procs):
            remaining = max(5.0, deadline - time.time())
            outs[i], _ = p.communicate(timeout=remaining)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        for i, p in enumerate(procs):
            if outs[i] is None:
                try:
                    outs[i], _ = p.communicate(timeout=10)
                except Exception:
                    outs[i] = "<no output>"
        pytest.fail(
            "2-process distributed run timed out\n"
            f"--- pid 0 ---\n{outs[0][-3000:]}\n--- pid 1 ---\n{outs[1][-3000:]}"
        )
    for i, p in enumerate(procs):
        assert p.returncode == 0, (
            f"child {i} rc={p.returncode}\n{outs[i][-3000:]}"
        )
        assert "MULTIHOST_CHILD_OK" in outs[i], outs[i][-3000:]
