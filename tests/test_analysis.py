"""Golden-fixture tests for the static-analysis plane (tools/analysis).

Each checker runs against one clean fixture (zero findings) and one seeded
fixture whose violations are asserted by exact (code, line) — a checker that
drifts off its seeded locations is broken, not merely noisy. The baseline
round-trip covers the waiver lifecycle: match, staleness, the 10-entry cap,
and the mandatory reason. The self-check runs the real CLI over the
committed tree and demands a clean exit.
"""

import subprocess
import sys
from pathlib import Path

import pytest

from tools.analysis.checks import drift, hotpath, jit_boundary, protocol_check, threads
from tools.analysis.engine import (
    Finding,
    MAX_WAIVERS,
    REPO_ROOT,
    apply_baseline,
    load_baseline,
)

FX = REPO_ROOT / "tools" / "analysis" / "fixtures"


def codes_lines(findings):
    return sorted((f.code, f.line) for f in findings)


# ------------------------------------------------------------------ hotpath
def test_hotpath_clean_fixture():
    got = hotpath.scan_file(
        FX / "hotpath_clean.py", {"Ring.hot_send": hotpath.STRICT}, "fx"
    )
    assert got == []


def test_hotpath_bad_fixture_strict():
    got = hotpath.scan_file(
        FX / "hotpath_bad.py", {"Ring.hot_send": hotpath.STRICT}, "fx"
    )
    assert codes_lines(got) == [
        ("HP001", 7),   # f-string
        ("HP002", 8),   # .format
        ("HP003", 9),   # %-format
        ("HP004", 10),  # comprehension
        ("HP005", 11),  # non-empty dict literal
        ("HP006", 12),  # print
        ("HP007", 13),  # json.dumps
    ]
    assert all(f.symbol == "Ring.hot_send" for f in got)


def test_hotpath_fmt_tier_allows_containers():
    got = hotpath.scan_file(
        FX / "hotpath_bad.py", {"Ring.hot_send": hotpath.FMT}, "fx"
    )
    # The fmt tier still bans formatting/logging but tolerates the
    # comprehension (HP004) and dict literal (HP005).
    assert codes_lines(got) == [
        ("HP001", 7), ("HP002", 8), ("HP003", 9), ("HP006", 12), ("HP007", 13)
    ]


def test_hotpath_missing_manifest_entry_is_flagged():
    got = hotpath.scan_file(
        FX / "hotpath_clean.py", {"Ring.gone": hotpath.STRICT}, "fx"
    )
    assert [f.code for f in got] == ["HP000"]


# ---------------------------------------------------------------------- jit
def test_jit_clean_fixture():
    assert jit_boundary.scan_file(FX / "jit_clean.py", "fx") == []


def test_jit_bad_fixture():
    got = jit_boundary.scan_file(FX / "jit_bad.py", "fx")
    assert codes_lines(got) == [
        ("JB001", 9),   # print
        ("JB002", 10),  # time.time()
        ("JB003", 11),  # .item()
        ("JB004", 12),  # np.asarray
        ("JB005", 13),  # float()
    ]
    assert all(f.symbol == "_body" for f in got)


# ----------------------------------------------------------------- protocol
def test_protocol_clean_fixture():
    got = protocol_check.check_protocol_file(
        FX / "proto_clean.py", "fx", {"_HEADER": "HEADER_BYTES"}
    )
    assert got == []


def test_protocol_bad_fixture():
    got = protocol_check.check_protocol_file(
        FX / "proto_bad.py", "fx", {"_HEADER": "HEADER_BYTES"}
    )
    assert codes_lines(got) == [
        ("PC001", 4),   # calcsize 12 != declared 10
        ("PC002", 14),  # TRACE_KINDS names Protocol.Ghost
        ("PC003", 8),   # enum values [0, 1, 3] have a gap
    ]


def test_mailbox_fixtures():
    assert protocol_check.check_mailbox_file(FX / "mailbox_clean.py", "fx") == []
    got = protocol_check.check_mailbox_file(FX / "mailbox_bad.py", "fx")
    assert codes_lines(got) == [("PC010", 2), ("PC010", 4)]


def test_bare_slot_index_fixture():
    got = protocol_check.scan_slot_usage(FX / "slots_bad.py", "fx")
    assert codes_lines(got) == [("PC011", 5), ("PC011", 6)]


def test_real_protocol_and_mailbox_are_clean():
    # The acceptance bite: change _TRAILER's format or delete HEADER_BYTES in
    # the real tree and this (and `make analyze`) must fail.
    assert (
        protocol_check.check_protocol_file(
            REPO_ROOT / "tpu_rl/runtime/protocol.py", "tpu_rl/runtime/protocol.py"
        )
        == []
    )
    assert (
        protocol_check.check_mailbox_file(
            REPO_ROOT / "tpu_rl/runtime/mailbox.py", "tpu_rl/runtime/mailbox.py"
        )
        == []
    )


# -------------------------------------------------------------------- drift
def test_drift_clean_fixture():
    code = drift.extract_code_metrics([FX / "drift_code_clean.py"], FX)
    doc = drift.extract_doc_metrics(FX / "drift_doc_clean.md")
    assert {n for n, _, _, _ in code} == {"relay-frames", "queue-depth"}
    assert drift.compare_metrics(code, doc, "fx.md") == []


def test_drift_bad_fixture():
    code = drift.extract_code_metrics([FX / "drift_code_bad.py"], FX)
    doc = drift.extract_doc_metrics(FX / "drift_doc_bad.md")
    got = drift.compare_metrics(code, doc, "fx.md")
    assert codes_lines(got) == [
        ("DR001", 7),  # orphan-metric in code, not in doc
        ("DR002", 6),  # ghost-metric documented, not in code
        ("DR003", 6),  # relay-frames registered as both counter and gauge
    ]


def test_config_fixture():
    got = drift.check_config(FX / "config_bad.py", "fx", exempt={})
    assert codes_lines(got) == [("DR010", 6)]
    assert got[0].symbol == "Config.batch"
    # A stale exemption (field no longer exists) is itself a finding.
    got = drift.check_config(FX / "config_bad.py", "fx", exempt={"zzz": "gone"})
    assert ("DR010", 1) in codes_lines(got)


def test_cli_fixture():
    got = drift.check_cli(FX / "cli_bad.py", "fx", {"lr"})
    by_code = {f.code: f for f in got}
    assert set(by_code) == {"DR011", "DR012", "DR013"}
    assert by_code["DR011"].symbol == "args.batch"
    assert by_code["DR012"].symbol == "--dead-flag"
    assert by_code["DR013"].symbol == "ghost_field"


# ------------------------------------------------------------------ threads
def test_threads_clean_fixture():
    got = threads.scan_file(FX / "threads_clean.py", {"W._run": frozenset()}, "fx")
    assert got == []


def test_threads_bad_fixture():
    got = threads.scan_file(FX / "threads_bad.py", {"W._run": frozenset()}, "fx")
    assert codes_lines(got) == [("TH001", 6), ("TH001", 9)]
    # The allowlist clears exactly those findings.
    got = threads.scan_file(
        FX / "threads_bad.py", {"W._run": frozenset({"count"})}, "fx"
    )
    assert got == []


def test_threads_missing_entry_is_flagged():
    got = threads.scan_file(FX / "threads_clean.py", {"W.gone": frozenset()}, "fx")
    assert [f.code for f in got] == ["TH000"]


# ----------------------------------------------------------------- baseline
def _waiver_toml(n, reason='reason = "justified"'):
    entry = (
        '[[waiver]]\ncheck = "hotpath"\ncode = "HP001"\n'
        f'path = "tpu_rl/x.py"\n{reason}\n'
    )
    return entry * n


def test_baseline_round_trip(tmp_path):
    p = tmp_path / "baseline.toml"
    p.write_text(_waiver_toml(1))
    waivers = load_baseline(p)
    assert len(waivers) == 1 and waivers[0].symbol == "*"
    hit = Finding("hotpath", "HP001", "tpu_rl/x.py", 10, "A.f", "m")
    miss = Finding("hotpath", "HP002", "tpu_rl/x.py", 11, "A.f", "m")
    kept, waived, stale = apply_baseline([hit, miss], waivers)
    assert kept == [miss] and waived == [hit] and stale == []
    # A waiver that matches nothing is reported stale.
    kept, waived, stale = apply_baseline([miss], waivers)
    assert kept == [miss] and waived == [] and stale == waivers


def test_baseline_requires_reason(tmp_path):
    p = tmp_path / "baseline.toml"
    p.write_text(_waiver_toml(1, reason='reason = ""'))
    with pytest.raises(ValueError, match="no reason"):
        load_baseline(p)


def test_baseline_caps_waivers(tmp_path):
    p = tmp_path / "baseline.toml"
    p.write_text(_waiver_toml(MAX_WAIVERS + 1))
    with pytest.raises(ValueError, match="cap"):
        load_baseline(p)


def test_committed_baseline_loads_within_cap():
    assert len(load_baseline()) <= MAX_WAIVERS


# --------------------------------------------------------------- self-check
def test_repo_is_clean_under_the_full_suite():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.analysis"],
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
