"""Quantized serving params (ISSUE 16): tree transforms, idempotence, the
spec map, and — the bar that matters — POLICY parity of the quantized act
path against f32 at trained-policy-like logit margins: bf16/int8 serving
must not flip actions or drift log-probs beyond sampling noise."""

import jax
import jax.numpy as jnp
import numpy as np

from tests.conftest import small_config
from tpu_rl.models.families import build_family
from tpu_rl.models.quant import (
    QUANT_MODES,
    dequantize_tree,
    is_q8_leaf,
    quant_spec,
    quantize_tree,
    tree_bytes,
)


def _params(cfg):
    family = build_family(cfg)
    return family, family.init_params(jax.random.key(0), seq_len=cfg.seq_len)


# ------------------------------------------------------------ transforms
class TestQuantizeTree:
    def test_f32_is_identity(self):
        _, params = _params(small_config())
        out = quantize_tree(params["actor"], "f32")
        for a, b in zip(
            jax.tree.leaves(params["actor"]), jax.tree.leaves(out),
            strict=True,
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_bf16_casts_float_leaves(self):
        _, params = _params(small_config())
        out = quantize_tree(params["actor"], "bf16")
        for leaf in jax.tree.leaves(out):
            assert leaf.dtype == jnp.bfloat16

    def test_int8_quantizes_matrices_keeps_biases(self):
        _, params = _params(small_config())
        out = quantize_tree(params["actor"], "int8")
        n_q8 = 0
        for leaf in jax.tree.leaves(out, is_leaf=is_q8_leaf):
            if is_q8_leaf(leaf):
                assert leaf["q8"].dtype == jnp.int8
                assert leaf["q8"].ndim >= 2
                n_q8 += 1
            else:
                # biases and scalars stay full precision
                assert leaf.ndim < 2 and leaf.dtype == jnp.float32
        assert n_q8 >= 4  # torso, x_proj, recurrent, heads

    def test_idempotent(self):
        _, params = _params(small_config())
        for mode in QUANT_MODES:
            once = quantize_tree(params["actor"], mode)
            twice = quantize_tree(once, mode)
            for a, b in zip(
                jax.tree.leaves(once, is_leaf=is_q8_leaf),
                jax.tree.leaves(twice, is_leaf=is_q8_leaf),
                strict=True,
            ):
                if is_q8_leaf(a):
                    np.testing.assert_array_equal(
                        np.asarray(a["q8"]), np.asarray(b["q8"])
                    )
                else:
                    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_dequantize_roundtrip_error_bounded(self):
        _, params = _params(small_config())
        q = quantize_tree(params["actor"], "int8")
        deq = dequantize_tree(q)
        for a, b in zip(
            jax.tree.leaves(params["actor"]), jax.tree.leaves(deq),
            strict=True,
        ):
            a, b = np.asarray(a), np.asarray(b)
            assert b.dtype == np.float32
            # per-tensor symmetric: error <= scale/2 = max|w|/254 per entry
            bound = max(np.abs(a).max() / 254.0, 1e-7)
            assert np.abs(a - b).max() <= bound + 1e-7

    def test_bytes_shrink_with_mode(self):
        _, params = _params(small_config())
        f32 = tree_bytes(quantize_tree(params["actor"], "f32"))
        bf16 = tree_bytes(quantize_tree(params["actor"], "bf16"))
        int8 = tree_bytes(quantize_tree(params["actor"], "int8"))
        assert int8 < bf16 < f32

    def test_quant_spec_paths(self):
        _, params = _params(small_config())
        spec = quant_spec(quantize_tree(params["actor"], "int8"))
        assert spec, "spec map empty"
        assert any("kernel" in k for k in spec)
        dtypes = {dtype for dtype, _shape in spec.values()}
        assert dtypes == {"int8", "float32"}, dtypes
        # every q8 row keeps its pre-quantization matrix shape
        assert all(
            len(shape) >= 2
            for dtype, shape in spec.values() if dtype == "int8"
        )


# ---------------------------------------------------------- policy parity
def _margin_params(cfg, family, scale=4.0, seed=0):
    """Init params with the logits head scaled up: random-init logits are
    near-uniform, where ANY noise flips the argmax — scaling the head
    recreates the decisive margins a trained policy has, which is the
    regime the >=99% agreement bar is specified against."""
    params = family.init_params(jax.random.key(seed), seq_len=cfg.seq_len)
    actor = jax.tree_util.tree_map(lambda x: x, params["actor"])  # copy
    head = actor["params"]["logits"]
    head["kernel"] = head["kernel"] * scale
    return actor


class TestQuantParity:
    ROWS = 512

    def _act(self, cfg, family, actor_params, mode):
        obs = np.asarray(
            jax.random.normal(
                jax.random.key(7), (self.ROWS, int(cfg.obs_shape[0]))
            )
        )
        hw, cw = family.carry_widths
        h = jnp.zeros((self.ROWS, hw))
        c = jnp.zeros((self.ROWS, cw))
        served = dequantize_tree(quantize_tree(actor_params, mode))
        return family.act(
            {"actor": served}, jnp.asarray(obs), h, c, jax.random.key(3)
        )

    def test_discrete_argmax_agreement_and_logp_drift(self):
        cfg = small_config(hidden_size=32)
        family = build_family(cfg)
        actor = _margin_params(cfg, family)
        _, logits_f32, lp_f32, _, _ = self._act(cfg, family, actor, "f32")
        for mode, atol in (("bf16", 0.05), ("int8", 0.08)):
            a_q, logits_q, lp_q, _, _ = self._act(cfg, family, actor, mode)
            agree = float(
                np.mean(
                    np.argmax(np.asarray(logits_q), -1)
                    == np.argmax(np.asarray(logits_f32), -1)
                )
            )
            assert agree >= 0.99, (mode, agree)
            drift = float(
                np.abs(np.asarray(lp_q) - np.asarray(lp_f32)).mean()
            )
            assert drift <= atol, (mode, drift)

    def test_discrete_same_key_same_actions_bf16(self):
        cfg = small_config(hidden_size=32)
        family = build_family(cfg)
        actor = _margin_params(cfg, family)
        a_f32, *_ = self._act(cfg, family, actor, "f32")
        a_bf16, *_ = self._act(cfg, family, actor, "bf16")
        same = float(np.mean(np.asarray(a_f32) == np.asarray(a_bf16)))
        assert same >= 0.99, same

    def test_continuous_mean_parity(self):
        cfg = small_config(
            algo="PPO-Continuous", is_continuous=True, hidden_size=32
        )
        family = build_family(cfg)
        actor = family.init_params(jax.random.key(0), seq_len=cfg.seq_len)[
            "actor"
        ]
        acts = {}
        for mode in QUANT_MODES:
            a, _, lp, _, _ = self._act(cfg, family, actor, mode)
            acts[mode] = np.asarray(a)
        # same PRNG key: sampled actions track the quantization error of mu
        np.testing.assert_allclose(
            acts["bf16"], acts["f32"], atol=5e-2
        )
        np.testing.assert_allclose(
            acts["int8"], acts["f32"], atol=1e-1
        )
        np.testing.assert_allclose(
            acts["bf16"].mean(0), acts["f32"].mean(0), atol=2e-2
        )
