"""Serving fast-path bench (ISSUE 16): schema + direction checks on
``bench.run_serving_fastpath`` — slow-marked (it boots six real
InferenceService configs); tier-1 stays fast. Directions asserted are the
ones the PR's acceptance bar names: buckets beat the padded baseline on
small flushes, the ratchet stays at 0 recompiles across the whole matrix,
and quantized rows report the shrunken param footprint."""

import json

import pytest

import bench

pytestmark = pytest.mark.slow

ROW_KEYS = {
    "name", "inference_dtype", "inference_buckets", "act_kernel",
    "kernel_active", "acts_per_s", "p99_ms", "recompiles", "param_bytes",
    "bucket_flushes", "client_failures",
}
CASE_NAMES = [
    "baseline-f32", "bf16", "buckets", "composed-bf16-buckets",
    "int8-buckets", "pallas-composed",
]


@pytest.fixture(scope="module")
def doc(tmp_path_factory):
    out = tmp_path_factory.mktemp("serving") / "bench_serving.json"
    return bench.run_serving_fastpath(
        clients=2, envs_per_client=2, acts=60, port=30990,
        out_path=str(out),
    ), out


def test_schema_and_artifact(doc):
    result, out = doc
    assert [r["name"] for r in result["rows"]] == CASE_NAMES
    for row in result["rows"]:
        assert set(row) == ROW_KEYS, row["name"]
    on_disk = json.loads(out.read_text())
    assert on_disk["metric"] == result["metric"]
    assert on_disk["recorded_at"][:3] == "202"
    assert result["pad_rows"] == 256


def test_directions(doc):
    result, _ = doc
    by = {r["name"]: r for r in result["rows"]}
    # the serving ratchet: every config compiles pre-bind, then never again
    assert result["recompiles_total"] == 0
    assert result["client_failures_total"] == 0
    # small flushes must dispatch the small bucket, never the 256 pad
    assert set(by["composed-bf16-buckets"]["bucket_flushes"]) == {"8"}
    assert set(by["baseline-f32"]["bucket_flushes"]) == {"256"}
    # quantization shrinks the served tree: int8 < bf16 < f32
    assert by["int8-buckets"]["param_bytes"] \
        < by["bf16"]["param_bytes"] < by["baseline-f32"]["param_bytes"]
    # the composed fast path beats the PR 12 padded baseline on throughput
    # (the acceptance capture in bench_serving.cpu.json shows >= 1.5x; the
    # light in-test shape keeps a safety margin against 1-core CI noise)
    assert result["composed_speedup"] >= 1.2, result["composed_speedup"]
    # ... at a tail no worse than the baseline's
    assert result["composed_p99_ratio"] is not None
    assert result["composed_p99_ratio"] <= 1.1, result["composed_p99_ratio"]


def test_cpu_rows_never_claim_the_kernel(doc):
    result, _ = doc
    by = {r["name"]: r for r in result["rows"]}
    assert by["pallas-composed"]["act_kernel"] == "pallas"
    if result["device_kind"].lower().startswith(("cpu", "host")):
        assert by["pallas-composed"]["kernel_active"] is False
