"""Subprocess body for the real N-process ``jax.distributed`` tests.

Run as: ``python tests/multihost_child.py <process_id> <coordinator_port>
[<num_processes>=2]``. Each process contributes 2 virtual CPU devices -> a
``2N``-device global mesh. Validates, with ACTUAL cross-process collectives
(gloo):

1. ``tpu_rl.parallel.multihost.init_multihost`` brings up the runtime;
2. the DP learner feed: ``host_local_batch_to_global`` under ``P("data")``
   (contiguous-rows assumption) + ``make_parallel_train_step`` over the
   global mesh == plain single-device jit on the same global batch;
3. the sequence-parallel feed: ``P("data","seq")`` placement (non-batch
   index dims preserved — the round-2 fix) + ring attention whose K/V
   rotation crosses the process boundary == single-device full attention;
4. the PRODUCTION service feed: ``LearnerService._to_batch`` with the
   multihost placement armed (``_setup_multihost_feed``) places this host's
   raw shm-style rows as the correct slice of the global array — the same
   train step through the service's own batching code == the oracle.

Not collected by pytest (no ``test_`` prefix); driven by
``tests/test_multihost.py``.
"""

from __future__ import annotations

import os
import sys


def main() -> None:
    pid = int(sys.argv[1])
    port = sys.argv[2]
    nprocs = int(sys.argv[3]) if len(sys.argv) > 3 else 2
    os.environ["JAX_PLATFORMS"] = "cpu"
    # 2 virtual CPU devices per process. Must be an XLA flag set before jax
    # imports (the parent strips any inherited XLA_FLAGS): this jax version
    # has no jax_num_cpu_devices config option.
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

    import jax

    # The TPU plugin here ignores JAX_PLATFORMS (see tpu_rl.utils.platform);
    # config-force the CPU platform BEFORE the distributed runtime starts.
    jax.config.update("jax_platforms", "cpu")

    from tpu_rl.parallel.multihost import init_multihost, is_multihost

    init_multihost(
        coordinator=f"127.0.0.1:{port}", num_processes=nprocs, process_id=pid
    )
    assert is_multihost(), "process_count must be > 1 after init_multihost"
    assert len(jax.devices()) == 2 * nprocs, jax.devices()
    assert len(jax.local_devices()) == 2

    import jax.numpy as jnp
    import numpy as np

    from tpu_rl.algos.registry import get_algo
    from tpu_rl.config import Config
    from tpu_rl.parallel.dp import (
        make_parallel_train_step,
        make_sp_train_step,
        replicate,
    )
    from tpu_rl.parallel.mesh import batch_sharding, make_mesh
    from tpu_rl.parallel.multihost import host_local_batch_to_global
    from tpu_rl.types import BATCH_FIELDS, Batch

    # ------------- 2. DP path: global batch 8 rows, 8/nprocs per host ------
    cfg = Config.from_dict(
        dict(
            algo="IMPALA", hidden_size=16, seq_len=5, batch_size=8,
            obs_shape=(4,), action_space=2,
        )
    )
    family, state, train_step = get_algo(cfg.algo).build(cfg, jax.random.key(0))

    rng = np.random.default_rng(0)  # same seed both hosts -> same global batch
    zb = Batch.zeros(
        cfg.batch_size, cfg.seq_len, cfg.obs_shape, cfg.action_space,
        cfg.hidden_size,
    )
    global_batch = zb.replace(
        obs=jnp.asarray(rng.normal(size=zb.obs.shape).astype(np.float32)),
        act=jnp.asarray(
            rng.integers(0, 2, size=zb.act.shape).astype(np.float32)
        ),
        rew=jnp.asarray(rng.normal(size=zb.rew.shape).astype(np.float32) * 0.1),
        log_prob=jnp.full(zb.log_prob.shape, -float(np.log(2.0))),
    )
    key = jax.random.key(7)

    # Single-device oracle on the full global batch (local jit, cpu:0).
    s_ref, m_ref = jax.jit(train_step)(state, global_batch, key)
    loss_ref = float(np.asarray(m_ref["loss"]))

    # DP over the 2N-device global mesh, each host feeding its own rows.
    mesh = make_mesh(2 * nprocs)
    pstep = make_parallel_train_step(train_step, mesh, cfg)
    rows = cfg.batch_size // nprocs
    local_rows = {
        f: np.asarray(getattr(global_batch, f))[pid * rows:(pid + 1) * rows]
        for f in BATCH_FIELDS
    }
    fed = Batch(**host_local_batch_to_global(local_rows, batch_sharding(mesh)))
    _f2, state2, _t2 = get_algo(cfg.algo).build(cfg, jax.random.key(0))
    state2 = replicate(state2, mesh)
    key_r = replicate(key, mesh)
    s_dp, m_dp = pstep(state2, fed, key_r)
    loss_dp = float(np.asarray(m_dp["loss"]))
    assert abs(loss_dp - loss_ref) < 1e-4 * max(1.0, abs(loss_ref)), (
        loss_dp, loss_ref,
    )

    # ---------- 3. Seq-sharded path: (data=nprocs, seq=2) mesh, ring -------
    from tpu_rl.parallel import make_sp_mesh

    n_data, n_seq = nprocs, 2  # uses every device: n_data * n_seq == 2N
    cfg_sp = Config.from_dict(
        dict(
            algo="PPO", model="transformer", attention_impl="ring",
            hidden_size=16, n_heads=2, n_layers=1, seq_len=8,
            batch_size=max(4, n_data),
            obs_shape=(4,), action_space=2, mesh_data=n_data, mesh_seq=n_seq,
        )
    )
    sp_mesh = make_sp_mesh(n_data, n_seq)
    fam_sp, state_sp, step_sp = get_algo("PPO").build(
        cfg_sp, jax.random.key(1), mesh=sp_mesh
    )
    rng2 = np.random.default_rng(1)
    B, S = cfg_sp.batch_size, cfg_sp.seq_len
    firsts = np.zeros((B, S, 1), np.float32)
    firsts[:, 0] = 1.0
    gb = dict(
        obs=rng2.normal(size=(B, S, 4)).astype(np.float32),
        act=rng2.integers(0, 2, size=(B, S, 1)).astype(np.float32),
        rew=(rng2.normal(size=(B, S, 1)) * 0.1).astype(np.float32),
        logits=np.zeros((B, S, 2), np.float32),
        log_prob=np.full((B, S, 1), -float(np.log(2.0)), np.float32),
        is_fir=firsts,
        hx=np.zeros((B, S, 1), np.float32),
        cx=np.zeros((B, S, 1), np.float32),
    )

    # Single-device oracle: same params, full attention.
    cfg_full = cfg_sp.replace(attention_impl="full", mesh_data=1, mesh_seq=1)
    _ff, state_full, step_full = get_algo("PPO").build(
        cfg_full, jax.random.key(1)
    )
    key2 = jax.random.key(9)
    _sf, m_full = jax.jit(step_full)(
        state_full, Batch.from_mapping(gb), key2
    )
    loss_full = float(np.asarray(m_full["loss"]))

    from jax.sharding import NamedSharding, PartitionSpec as P
    from tpu_rl.parallel.sequence import DATA_AXIS, SEQ_AXIS

    sp_sharding = NamedSharding(sp_mesh, P(DATA_AXIS, SEQ_AXIS))
    # Host rows of the (data, seq)-sharded batch; the trailing (seq) dim
    # stays global-sized locally and is sliced per device by
    # host_local_batch_to_global (the round-2 fix under test).
    sp_rows = cfg_sp.batch_size // nprocs
    local_sp = {
        f: v[pid * sp_rows:(pid + 1) * sp_rows] for f, v in gb.items()
    }
    fed_sp = Batch(**host_local_batch_to_global(local_sp, sp_sharding))
    pstep_sp = make_sp_train_step(step_sp, sp_mesh, cfg_sp)
    state_sp = replicate(state_sp, sp_mesh)
    s_sp, m_sp = pstep_sp(state_sp, fed_sp, replicate(key2, sp_mesh))
    loss_sp = float(np.asarray(m_sp["loss"]))
    assert abs(loss_sp - loss_full) < 5e-4 * max(1.0, abs(loss_full)), (
        loss_sp, loss_full,
    )

    # ------- 4. Production service feed: LearnerService._to_batch ---------
    # The service arms multihost placement in run() via _setup_multihost_feed
    # (jax.process_count() > 1); drive the same code path directly: raw
    # host-local rows (what its shm store consume() yields on this host) must
    # place as THIS host's slice of the global batch, and the DP step through
    # the service's own batching must match the single-device oracle.
    from tpu_rl.runtime.learner_service import LearnerService

    svc = LearnerService(cfg, handles=None, model_port=0)
    svc._place_global = None
    svc._setup_multihost_feed(batch_sharding(mesh))
    assert svc._place_global is not None, "service must arm multihost feed"
    fed_svc = svc._to_batch(local_rows)
    _f3, state3, _t3 = get_algo(cfg.algo).build(cfg, jax.random.key(0))
    s_svc, m_svc = pstep(replicate(state3, mesh), fed_svc, replicate(key, mesh))
    loss_svc = float(np.asarray(m_svc["loss"]))
    assert abs(loss_svc - loss_ref) < 1e-4 * max(1.0, abs(loss_ref)), (
        loss_svc, loss_ref,
    )

    print(
        f"MULTIHOST_CHILD_OK pid={pid} nprocs={nprocs} loss_dp={loss_dp:.6f} "
        f"loss_sp={loss_sp:.6f} loss_svc={loss_svc:.6f}",
        flush=True,
    )


if __name__ == "__main__":
    main()
