"""Subprocess "virtual pod host" body for tests/test_colocated_multihost.py.

Each invocation is one host of a CPU pod: XLA_FLAGS pins the per-host
device count BEFORE jax imports, and ``Config.multihost`` brings the host
into the jax.distributed runtime (gloo collectives) inside
``ColocatedLoop.__init__`` — the production bring-up path, not a test
shim.

    python colocated_multihost_child.py <mode> <pid> <nprocs> <ndev> \
        <port> <workdir> <max_updates>

Modes:
    parity  — run the fused pod-Anakin loop for <max_updates> updates with
              no checkpointing, then dump every train-state leaf to
              ``<workdir>/params_<nprocs>_<pid>.npz`` and print
              ``CHILD_PARAMS sha=...`` (sha256 over the leaf bytes).
    train   — run with two-phase checkpointing into <workdir>; meant to be
              SIGKILLed mid-run by the parent test.
    resume  — same config as train; restores the newest committed
              checkpoint, prints ``CHILD_RESUME pid=.. start_it=..
              epoch=..``, and runs to <max_updates>.

Every successful exit prints CHILD_OK.
"""

import hashlib
import os
import sys

mode = sys.argv[1]
pid = int(sys.argv[2])
nprocs = int(sys.argv[3])
ndev = int(sys.argv[4])
port = sys.argv[5]
workdir = sys.argv[6]
max_updates = int(sys.argv[7])

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    f"--xla_force_host_platform_device_count={ndev}"
)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
import numpy as np  # noqa: E402

from tpu_rl.config import Config  # noqa: E402
from tpu_rl.runtime.colocated import ColocatedLoop  # noqa: E402


def build_config(model_dir: str | None) -> Config:
    mh = None
    if nprocs > 1:
        mh = {
            "coordinator": f"127.0.0.1:{port}",
            "num_processes": nprocs,
            "process_id": pid,
        }
    return Config.from_dict(
        dict(
            env="CartPole-v1", env_mode="colocated", algo="PPO",
            hidden_size=32, seq_len=8, batch_size=32,
            lr=3e-4, entropy_coef=0.001, reward_scale=0.1,
            time_horizon=100, loss_log_interval=10**9,
            mesh_data=nprocs * ndev,
            multihost=mh,
            model_dir=model_dir,
            model_save_interval=5,
        )
    )


def main() -> None:
    model_dir = None if mode == "parity" else os.path.join(workdir, "ckpt")
    loop = ColocatedLoop(build_config(model_dir), seed=0,
                         max_updates=max_updates)
    # log=True in resume mode: the chief's "resumed from committed
    # checkpoint" line is part of what the parent test pins (and the loop
    # itself silences every non-chief process).
    out = loop.run(log=(mode == "resume"))

    if mode == "parity":
        leaves = [
            np.asarray(x)
            for x in jax.tree_util.tree_leaves(jax.device_get(loop.state))
        ]
        h = hashlib.sha256()
        for leaf in leaves:
            h.update(leaf.tobytes())
        np.savez(
            os.path.join(workdir, f"params_{nprocs}_{pid}.npz"),
            *leaves,
        )
        print(f"CHILD_PARAMS sha={h.hexdigest()}", flush=True)
    elif mode == "resume":
        print(
            f"CHILD_RESUME pid={pid} start_it={loop._start_it} "
            f"epoch={loop.run_epoch}",
            flush=True,
        )
    print(
        f"CHILD_OK mode={mode} pid={pid} updates={out['updates']} "
        f"episodes={out['episodes']}",
        flush=True,
    )


if __name__ == "__main__":
    main()
