"""Telemetry plane over the real runtime: the Model-version echo through a
live worker -> storage hop, and the cluster e2e acceptance test — scrape
/metrics mid-run and find Prometheus-parseable samples from every role,
including a nonzero policy-staleness observation.

Port range: this module owns 289xx (test_runtime owns 29xxx,
test_inference_service 30xxx).
"""

import json
import os
import re
import threading
import time
import urllib.error
import urllib.request

import pytest

from tests.conftest import small_config
from tests.test_runtime import _cluster_cfg, _machines
from tpu_rl.obs import STALENESS_HIST, TelemetryAggregator
from tpu_rl.runtime.protocol import Protocol


# ----------------------------------------------------- worker -> storage echo
@pytest.mark.timeout(240)
def test_model_version_echo_worker_to_storage():
    """Tag a live Model broadcast with ver=7; a real Worker must echo it into
    every subsequent RolloutBatch, and feeding those frames through the real
    storage ingest must land a policy-staleness observation."""
    import jax

    from tpu_rl.data.assembler import RolloutAssembler
    from tpu_rl.data.layout import BatchLayout
    from tpu_rl.models.families import build_family
    from tpu_rl.runtime.storage import LearnerStorage
    from tpu_rl.runtime.transport import MODEL_HWM, Pub, Sub
    from tpu_rl.runtime.worker import Worker

    base = 28900
    cfg = small_config(
        env="CartPole-v1", algo="PPO", worker_num_envs=2,
        worker_step_sleep=0.0, time_horizon=8,
        # enables the worker's registry/emitter (no sockets open worker-side)
        telemetry_port=18126, telemetry_interval_s=0.2,
    )
    relay_sub = Sub("127.0.0.1", base, bind=True)  # plays the manager
    model_pub = Pub("127.0.0.1", base + 1, bind=True, hwm=MODEL_HWM)
    stop = threading.Event()
    w = Worker(
        cfg, worker_id=0, manager_ip="127.0.0.1", manager_port=base,
        learner_ip="127.0.0.1", model_port=base + 1, stop_event=stop,
    )
    wt = threading.Thread(target=w.run, daemon=True)
    wt.start()

    family = build_family(cfg)
    host_actor = jax.device_get(
        family.init_params(jax.random.key(0), seq_len=cfg.seq_len)["actor"]
    )
    pub_stop = threading.Event()

    def keep_publishing():  # re-send: ZMQ slow-joiner drops early frames
        while not pub_stop.is_set():
            model_pub.send(Protocol.Model, {"actor": host_actor, "ver": 7})
            time.sleep(0.05)

    pt = threading.Thread(target=keep_publishing, daemon=True)
    pt.start()

    echoed, telemetry = [], []
    try:
        deadline = time.time() + 180
        while time.time() < deadline and len(echoed) < 5:
            got = relay_sub.recv(timeout_ms=500)
            if got is None:
                continue
            proto, payload = got
            if proto == Protocol.RolloutBatch and payload.get("ver") == 7:
                echoed.append(payload)
            elif proto == Protocol.Telemetry:
                telemetry.append(payload)
    finally:
        pub_stop.set()
        stop.set()
        pt.join(timeout=10)
        wt.join(timeout=30)
        relay_sub.close()
        model_pub.close()
    assert len(echoed) >= 5, "worker never echoed the broadcast version"
    assert all(p["wid"] == 0 for p in echoed)

    # Storage edge: the echoed frames must produce staleness observations.
    st = LearnerStorage(cfg, handles=None, learner_port=0)
    st.aggregator = TelemetryAggregator()  # plane on, no HTTP side effects
    assembler = RolloutAssembler(
        BatchLayout.from_config(cfg), lag_sec=cfg.rollout_lag_sec
    )
    for payload in echoed:
        st._ingest(Protocol.RolloutBatch, payload, assembler)
    agg = st.aggregator
    assert agg.max_version == 7  # echo alone ratchets the bound
    h = agg.registry.histogram(STALENESS_HIST, labels={"wid": "0"})
    assert h.count == len(echoed) and h.sum == 0.0  # acting at max version

    # Satellite: the worker's CLOCK-driven snapshots rode the same channel.
    assert telemetry, "worker emitted no Telemetry frames"
    assert telemetry[0]["role"] == "worker" and telemetry[0]["wid"] == 0
    st._ingest(Protocol.Telemetry, telemetry[0], assembler)
    assert any(s.get("role") == "worker" for s, _ in agg.all_snapshots())


# ------------------------------------------------------------- cluster e2e
def _scrape(url: str, timeout: float = 3.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read().decode()
    except (urllib.error.URLError, ConnectionError, OSError):
        return None, ""


_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?[0-9].*$|^#.*$"
)


# slow: boots a full supervised cluster (~85s on this one-core box) and
# the same surface is gated in CI by `make obs-smoke`/`make trace-smoke`;
# the tier-1 budget goes to the unit-level obs tests.
@pytest.mark.slow
@pytest.mark.timeout(300)
def test_cluster_telemetry_scrape_end_to_end(tmp_path):
    """Acceptance: boot the full local cluster with the plane on, scrape
    /metrics mid-run, and find Prometheus-parseable samples from worker,
    manager, storage AND learner — including a nonzero
    policy-staleness-updates observation — then validate /healthz,
    result_dir/telemetry.json and the learner's Chrome trace. With
    trace_sample_n on (ISSUE 5 acceptance), the run must also leave a merged
    fleet_trace.json whose sampled rollout chains link worker, manager,
    storage and learner spans with Chrome flow events."""
    from tpu_rl.runtime.runner import local_cluster

    base, tport = 28920, 28960
    cfg = _cluster_cfg(
        tmp_path,
        telemetry_port=tport,
        telemetry_interval_s=0.5,
        telemetry_stale_s=120.0,  # slow CI must not flap /healthz
        result_dir=str(tmp_path / "run"),
        loss_log_interval=2,
        trace_sample_n=2,  # every 2nd worker tick carries a trace trailer
    )
    assert cfg.telemetry_enabled
    sup = local_cluster(cfg, _machines(base), max_updates=6)
    metrics_url = f"http://127.0.0.1:{tport}/metrics"
    staleness_count = re.compile(
        r"^policy_staleness_updates_count\{[^}]*\} (\d+)$", re.M
    )
    try:
        learner = next(c for c in sup.children if c.name == "learner")
        text, ok = "", False
        deadline = time.time() + 240
        while time.time() < deadline:
            _, text = _scrape(metrics_url)
            counts = [int(m) for m in staleness_count.findall(text)]
            if (
                all(f'role="{r}"' in text
                    for r in ("worker", "manager", "storage", "learner"))
                and any(c > 0 for c in counts)
            ):
                ok = True
                break
            time.sleep(0.5)
        assert ok, f"per-role samples never converged; last scrape:\n{text}"
        # every exposition line is Prometheus-parseable
        for line in text.splitlines():
            assert _SAMPLE_RE.match(line), f"unparseable line: {line!r}"

        status, body = _scrape(f"http://127.0.0.1:{tport}/healthz")
        assert status in (200, 503)
        doc = json.loads(body)
        assert {"worker", "manager", "storage", "learner"} <= set(doc["roles"])
        for role in doc["roles"].values():
            assert role["sources"] >= 1

        # /tracez: the storage edge's live span ring + clock estimates.
        status, body = _scrape(f"http://127.0.0.1:{tport}/tracez")
        assert status == 200
        tz = json.loads(body)
        assert tz["role"] == "storage" and tz["trace"] is not None

        while time.time() < deadline and learner.proc.is_alive():
            time.sleep(1.0)
        assert not learner.proc.is_alive() and learner.proc.exitcode == 0
    finally:
        sup.stop()

    # Post-run artifacts: the rolling JSON snapshot and the Chrome trace.
    tele = json.loads((tmp_path / "run" / "telemetry.json").read_text())
    roles = {src["role"] for src in tele["sources"]}
    assert {"worker", "storage", "learner"} <= roles
    trace = json.loads((tmp_path / "run" / "trace.json").read_text())
    names = {ev["name"] for ev in trace["traceEvents"] if ev["ph"] == "X"}
    assert {"queue-wait", "train-step"} <= names
    assert os.path.getsize(tmp_path / "run" / "telemetry.json") > 0

    # ISSUE 5 acceptance: the storage edge auto-merged the fleet trace at
    # shutdown; re-merge now that EVERY role has joined (late final dumps)
    # and require at least one complete clock-corrected lineage chain.
    from tpu_rl.obs import merge_result_dir
    from tpu_rl.obs.merge import MERGED_NAME

    run = tmp_path / "run"
    assert (run / MERGED_NAME).exists(), "storage did not auto-merge"
    summary = merge_result_dir(str(run))
    assert {"worker", "manager", "storage", "learner"} <= set(summary["roles"])
    assert summary["flows"] >= 1
    fleet = json.loads((run / MERGED_NAME).read_text())  # valid JSON on disk
    chains: dict = {}
    for ev in fleet["traceEvents"]:
        if ev.get("cat") == "lineage":
            chains.setdefault(ev["id"], []).append(ev["args"]["hop"])
    assert any(
        {"worker-tick", "storage-ingest", "train-step"} <= set(hops)
        and ("relay-in" in hops or "relay-out" in hops)
        for hops in chains.values()
    ), f"no fully-linked rollout chain: {chains}"
    # clock sync saw the worker (full NTP loop rides Model + Telemetry)
    assert any(k.startswith("worker") for k in fleet["meta"]["clock"])
