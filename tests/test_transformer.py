"""Transformer long-context policy tests: unroll contract, PPO integration,
sequence-parallel train-step equivalence on the 8-device mesh, windowed act."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.conftest import small_config
from tpu_rl.algos.registry import get_algo
from tpu_rl.models.families import build_family
from tpu_rl.types import Batch


def _tf_config(**kw):
    base = dict(
        algo="PPO",
        model="transformer",
        hidden_size=32,
        n_heads=4,
        n_layers=2,
        seq_len=16,
        batch_size=8,
        obs_shape=(4,),
        action_space=2,
    )
    base.update(kw)
    return small_config(**base)


def _random_batch(cfg, rng, hx_width, cx_width):
    B, S = cfg.batch_size, cfg.seq_len
    firsts = np.zeros((B, S, 1), np.float32)
    firsts[:, 0] = 1.0
    for b in range(B):
        firsts[b, rng.integers(1, S)] = 1.0  # one mid-window seam
    return Batch(
        obs=jnp.asarray(rng.normal(size=(B, S, 4)).astype(np.float32)),
        act=jnp.asarray(
            rng.integers(0, cfg.action_space, size=(B, S, 1)).astype(np.float32)
        ),
        rew=jnp.asarray(rng.normal(size=(B, S, 1)).astype(np.float32) * 0.1),
        logits=jnp.zeros((B, S, cfg.action_space)),
        log_prob=jnp.full((B, S, 1), -np.log(cfg.action_space), jnp.float32),
        is_fir=jnp.asarray(firsts),
        hx=jnp.zeros((B, S, hx_width)),
        cx=jnp.zeros((B, S, cx_width)),
    )


class TestTransformerUnroll:
    def test_unroll_contract_shapes(self, rng):
        cfg = _tf_config()
        fam = build_family(cfg)
        params = fam.init_params(jax.random.key(0), seq_len=cfg.seq_len)
        obs = jnp.asarray(rng.normal(size=(2, cfg.seq_len, 4)).astype(np.float32))
        firsts = jnp.zeros((2, cfg.seq_len, 1))
        logits, value, carry = fam.actor_unroll(
            params["actor"], obs, None, firsts
        )
        assert logits.shape == (2, cfg.seq_len, 2)
        assert value.shape == (2, cfg.seq_len, 1)
        # log-softmax rows normalize
        np.testing.assert_allclose(
            np.exp(np.asarray(logits)).sum(-1), 1.0, atol=1e-5
        )

    def test_causality_of_unroll(self, rng):
        """Changing obs at t must not change logits before t."""
        cfg = _tf_config()
        fam = build_family(cfg)
        params = fam.init_params(jax.random.key(0), seq_len=cfg.seq_len)
        obs = jnp.asarray(rng.normal(size=(1, cfg.seq_len, 4)).astype(np.float32))
        firsts = jnp.zeros((1, cfg.seq_len, 1))
        l1, _, _ = fam.actor_unroll(params["actor"], obs, None, firsts)
        obs2 = obs.at[:, 10:].set(5.0)
        l2, _, _ = fam.actor_unroll(params["actor"], obs2, None, firsts)
        np.testing.assert_allclose(
            np.asarray(l1[:, :10]), np.asarray(l2[:, :10]), atol=1e-5
        )

    def test_ppo_train_step_decreases_loss_signal(self, rng):
        cfg = _tf_config()
        fam, state, train_step = get_algo("PPO").build(cfg, jax.random.key(0))
        step = jax.jit(train_step)
        from tpu_rl.data.layout import BatchLayout

        lay = BatchLayout.from_config(cfg)
        batch = _random_batch(cfg, rng, lay.hx, lay.cx)
        for _ in range(3):
            state, metrics = step(state, batch, jax.random.key(1))
        assert np.isfinite(float(metrics["loss"]))
        assert int(state.step) == 3


class TestSequenceParallelTrainStep:
    # slow: every case compiles a fresh (data=2, seq=4) shard_map train
    # step on 8 virtual devices (~8-10s each on this box); tier-1 keeps
    # the cheap SP validation test, the grad-equivalence matrix runs in
    # the slow tier.
    @pytest.mark.slow
    @pytest.mark.parametrize(
        "impl,algo",
        [("ring", "PPO"), ("ulysses", "PPO"), ("ring", "V-MPO")],
    )
    def test_sp_train_step_matches_single_device(self, devices, rng, impl, algo):
        """Full train step, transformer backbone: (data=2, seq=4) mesh
        result == single-device result. V-MPO is the sharding-hard case
        (VERDICT r4 #7): its per-timestep top-half advantage selection
        reduces over the data-sharded batch axis while the time axis is
        seq-sharded — both the threshold sort and the global psi softmax
        must cross the mesh."""
        from tpu_rl.data.layout import BatchLayout
        from tpu_rl.parallel import make_sp_mesh, make_sp_train_step

        cfg = _tf_config(
            algo=algo, attention_impl=impl, mesh_data=2, mesh_seq=4
        )
        lay = BatchLayout.from_config(cfg)
        batch = _random_batch(cfg, rng, lay.hx, lay.cx)
        key = jax.random.key(7)

        # single device reference (full attention, same params)
        cfg1 = cfg.replace(attention_impl="full", mesh_data=1, mesh_seq=1)
        _, state1, step1 = get_algo(algo).build(cfg1, jax.random.key(0))
        s1, m1 = jax.jit(step1)(state1, batch, key)

        mesh = make_sp_mesh(2, 4)
        _, state2, step2 = get_algo(algo).build(
            cfg, jax.random.key(0), mesh=mesh
        )
        pstep = make_sp_train_step(step2, mesh, cfg)
        s2, m2 = pstep(state2, batch, key)

        np.testing.assert_allclose(
            float(m1["loss"]), float(m2["loss"]), rtol=2e-4, atol=2e-5
        )
        for a, b in zip(
            jax.tree_util.tree_leaves(s1.params),
            jax.tree_util.tree_leaves(s2.params),
            strict=True,
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=3e-3, atol=3e-4
            )

    @pytest.mark.slow
    @pytest.mark.parametrize("impl", ["ring", "ulysses"])
    def test_bf16_sp_train_step_runs(self, devices, rng, impl):
        """bfloat16 compute composes with sharded attention (f32 softmax
        accumulators keep the scan carry dtype-stable)."""
        from tpu_rl.data.layout import BatchLayout
        from tpu_rl.parallel import make_sp_mesh, make_sp_train_step

        cfg = _tf_config(
            attention_impl=impl, mesh_data=2, mesh_seq=4,
            compute_dtype="bfloat16",
        )
        lay = BatchLayout.from_config(cfg)
        batch = _random_batch(cfg, rng, lay.hx, lay.cx)
        mesh = make_sp_mesh(2, 4)
        _, state, step = get_algo("PPO").build(cfg, jax.random.key(0), mesh=mesh)
        pstep = make_sp_train_step(step, mesh, cfg)
        state, metrics = pstep(state, batch, jax.random.key(7))
        assert np.isfinite(float(metrics["loss"]))
        assert {str(l.dtype) for l in jax.tree_util.tree_leaves(state.params)} == {
            "float32"
        }

    def test_sp_validates_divisibility(self, devices):
        from tpu_rl.parallel import make_sp_mesh, make_sp_train_step

        cfg = _tf_config(attention_impl="ring", seq_len=10)  # 10 % 4 != 0
        mesh = make_sp_mesh(2, 4)
        _, _, step = get_algo("PPO").build(cfg, jax.random.key(0), mesh=mesh)
        with pytest.raises(ValueError, match="seq"):
            make_sp_train_step(step, mesh, cfg)


class TestMixedPrecisionStructure:
    @pytest.mark.parametrize("impl", ["full", "blockwise"])
    def test_bf16_train_step_has_no_mixed_dtype_dots(self, impl):
        """Every dot_general in a bfloat16 transformer train step must take
        SAME-dtype operands: a mixed f32 x bf16 dot runs at f32 rate on the
        MXU, silently forfeiting the speedup bf16 mode exists for. (The
        measured instances: f32 softmax probabilities contracting against
        bf16 values, and f32 cotangents leaving the attention VJP into the
        bf16 projection backward — round-5 fixes in parallel/sequence.py
        ``_contract_dtype`` / ``_make_mp_einsum``; the LSTM analogue was
        pallas_lstm.mixed_dot.) f32 x f32 dots are fine (losses, heads);
        mixed pairs are the regression this pins, across EVERY dot in the
        jaxpr tree (structural traversal — see conftest)."""
        from tests.conftest import dot_operand_dtypes

        cfg = _tf_config(
            algo="PPO", attention_impl=impl, compute_dtype="bfloat16",
            batch_size=4,
        )
        from tests.test_algos import make_batch

        fam, state, step = get_algo("PPO").build(cfg, jax.random.key(0))
        batch = make_batch(cfg, fam)
        jaxpr = jax.make_jaxpr(step)(state, batch, jax.random.key(1))
        dots = dot_operand_dtypes(jaxpr)
        assert dots, "no dots found — jaxpr traversal broken?"
        mixed = [(a, b) for a, b in dots if a != b]
        assert not mixed, f"mixed-dtype dots: {mixed}"

    @pytest.mark.slow  # traces the full SP shard_map island (~3s)
    def test_bf16_ring_sp_train_step_has_no_mixed_dtype_dots(self, devices):
        """Same invariant through the sequence-parallel path: the ring
        attention shard_map island and its hand-written VJP (whose einsums
        are cast manually, not via _make_mp_einsum) — the traversal
        descends into shard_map/custom-VJP sub-jaxprs."""
        from tests.conftest import dot_operand_dtypes
        from tests.test_algos import make_batch
        from tpu_rl.parallel import make_sp_mesh

        cfg = _tf_config(
            algo="PPO", attention_impl="ring", compute_dtype="bfloat16",
            mesh_data=2, mesh_seq=4,
        )
        mesh = make_sp_mesh(2, 4)
        fam, state, step = get_algo("PPO").build(
            cfg, jax.random.key(0), mesh=mesh
        )
        batch = make_batch(cfg, fam)
        jaxpr = jax.make_jaxpr(step)(state, batch, jax.random.key(1))
        dots = dot_operand_dtypes(jaxpr)
        assert dots, "no dots found — jaxpr traversal broken?"
        mixed = [(a, b) for a, b in dots if a != b]
        assert not mixed, f"mixed-dtype dots: {mixed}"


class TestTransformerActing:
    def test_act_carry_protocol(self, rng):
        cfg = _tf_config(act_ctx=8)
        fam = build_family(cfg)
        params = fam.init_params(jax.random.key(0), seq_len=cfg.seq_len)
        act = jax.jit(fam.act)
        h = jnp.zeros((1, fam.carry_widths[0]))
        c = jnp.zeros((1, fam.carry_widths[1]))
        for t in range(12):
            obs = jnp.asarray(rng.normal(size=(1, 4)).astype(np.float32))
            a, logits, log_prob, h, c = act(params, obs, h, c, jax.random.key(t))
            assert a.shape == (1, 1)
            assert logits.shape == (1, 2)
            assert np.isfinite(np.asarray(logits)).all()
        assert float(c[0, -1]) == 12.0  # step counter (KV ring handles > ctx)

    def test_act_ignores_padding(self, rng):
        """With 0 cached steps, logits must not depend on stale cache bytes."""
        cfg = _tf_config(act_ctx=8)
        fam = build_family(cfg)
        params = fam.init_params(jax.random.key(0), seq_len=cfg.seq_len)
        obs = jnp.asarray(rng.normal(size=(1, 4)).astype(np.float32))
        kv, kv1 = fam.carry_widths
        c0 = jnp.zeros((1, kv1))
        h_zero = jnp.zeros((1, kv))
        h_junk = jnp.asarray(rng.normal(size=(1, kv)).astype(np.float32))
        # junk V caches too (their counter stays 0 = nothing valid)
        c_junk = c0.at[:, :-1].set(
            jnp.asarray(rng.normal(size=(kv1 - 1,)).astype(np.float32))
        )
        _, l1, _, _, _ = fam.act(params, obs, h_zero, c0, jax.random.key(0))
        _, l2, _, _, _ = fam.act(params, obs, h_junk, c_junk, jax.random.key(0))
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), atol=1e-5)

    @pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
    def test_kv_cache_matches_window_recompute(self, rng, dtype):
        """The KV-cached acting path must reproduce the full-window recompute
        path (float tolerance; bf16 within mixed-precision rounding) for
        every step of an episode that fits the context window — the O(ctx·d)
        vs O(ctx²·d) redesign changes cost, not math."""
        from functools import partial

        from tpu_rl.models.families import _act_transformer_window

        cfg = _tf_config(act_ctx=8, compute_dtype=dtype)
        ctx, obs_dim = cfg.effective_act_ctx, 4
        fam = build_family(cfg)
        params = fam.init_params(jax.random.key(0), seq_len=cfg.seq_len)
        act_kv = jax.jit(fam.act)
        act_win = jax.jit(
            partial(_act_transformer_window, fam.actor, ctx, obs_dim)
        )
        h_kv = jnp.zeros((1, fam.carry_widths[0]))
        c_kv = jnp.zeros((1, fam.carry_widths[1]))
        h_w = jnp.zeros((1, ctx * obs_dim))
        c_w = jnp.zeros((1, 1))
        tol = dict(rtol=1e-5, atol=1e-5) if dtype == "float32" else dict(
            rtol=0.05, atol=0.03
        )
        for t in range(ctx):  # full window-length episode
            obs = jnp.asarray(rng.normal(size=(1, obs_dim)).astype(np.float32))
            k = jax.random.key(100 + t)
            a1, l1, lp1, h_kv, c_kv = act_kv(params, obs, h_kv, c_kv, k)
            a2, l2, lp2, h_w, c_w = act_win(params, obs, h_w, c_w, k)
            np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), **tol)
            if dtype == "float32":
                np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))

    @pytest.mark.slow  # compiles both acting programs at ctx=256 (~3s)
    def test_kv_cache_is_cheaper(self):
        """Compiled FLOPs of one cached acting step must be far below the
        window-recompute step at long context (the point of the redesign)."""
        from functools import partial

        from tpu_rl.models.families import _act_transformer_window

        cfg = _tf_config(act_ctx=256, seq_len=16)
        ctx, obs_dim = cfg.effective_act_ctx, 4
        fam = build_family(cfg)
        params = fam.init_params(jax.random.key(0), seq_len=cfg.seq_len)
        obs = jnp.zeros((1, obs_dim))
        key = jax.random.key(0)

        def flops(fn, h, c):
            from tpu_rl.obs.perf import compiled_flops

            lowered = jax.jit(fn).lower(params, obs, h, c, key)
            return compiled_flops(lowered.compile())

        f_kv = flops(
            fam.act,
            jnp.zeros((1, fam.carry_widths[0])),
            jnp.zeros((1, fam.carry_widths[1])),
        )
        f_win = flops(
            partial(_act_transformer_window, fam.actor, ctx, obs_dim),
            jnp.zeros((1, ctx * obs_dim)),
            jnp.zeros((1, 1)),
        )
        if not (f_kv and f_win):
            pytest.skip("backend reports no FLOPs cost analysis")
        assert f_kv < f_win / 20, (f_kv, f_win)

    def test_bf16_kv_decode_runs(self, rng):
        """bf16 compute must compose with the float32 carry caches (the
        projections are cast back before the cache slice update)."""
        cfg = _tf_config(act_ctx=8, compute_dtype="bfloat16")
        fam = build_family(cfg)
        params = fam.init_params(jax.random.key(0), seq_len=cfg.seq_len)
        act = jax.jit(fam.act)
        h = jnp.zeros((1, fam.carry_widths[0]))
        c = jnp.zeros((1, fam.carry_widths[1]))
        for t in range(3):
            obs = jnp.asarray(rng.normal(size=(1, 4)).astype(np.float32))
            _a, logits, _lp, h, c = act(params, obs, h, c, jax.random.key(t))
            assert np.isfinite(np.asarray(logits)).all()
        assert h.dtype == jnp.float32 and c.dtype == jnp.float32

    def test_worker_batch_layout_roundtrip(self):
        """Transformer batches ship 1-float carry placeholders (the KV caches
        stay worker-local); the family knows the real carry widths."""
        from tpu_rl.data.layout import BatchLayout

        cfg = _tf_config(act_ctx=8)
        lay = BatchLayout.from_config(cfg)
        assert lay.hx == 1 and lay.cx == 1
        fam = build_family(cfg)
        kv = cfg.n_layers * 8 * cfg.hidden_size
        assert fam.carry_widths == (kv, kv + 1)
        assert not fam.store_carry


class TestVectorizedTransformerActing:
    def test_batched_act_matches_per_row_acting(self, rng):
        """Per-row KV-cache counters: a batch of envs at DIFFERENT episode
        steps, acted in one call, must produce exactly the logits each env
        would get acted alone (the vectorized worker's correctness
        contract). Rows are desynchronized by resetting env 1's carry
        mid-run (fresh episode), as the worker does."""
        cfg = _tf_config(act_ctx=8)
        fam = build_family(cfg)
        params = fam.init_params(jax.random.key(0), seq_len=cfg.seq_len)
        act = jax.jit(fam.act)
        hw, cw = fam.carry_widths
        N = 3
        h = jnp.zeros((N, hw))
        c = jnp.zeros((N, cw))
        # independent single-env references
        hs = [jnp.zeros((1, hw)) for _ in range(N)]
        cs = [jnp.zeros((1, cw)) for _ in range(N)]
        for t in range(10):
            obs = jnp.asarray(rng.normal(size=(N, 4)).astype(np.float32))
            key = jax.random.key(t)
            _a, logits, _lp, h, c = act(params, obs, h, c, key)
            for i in range(N):
                _ai, li, _lpi, hs[i], cs[i] = act(
                    params, obs[i : i + 1], hs[i], cs[i], key
                )
                np.testing.assert_allclose(
                    np.asarray(logits[i]), np.asarray(li[0]),
                    rtol=1e-5, atol=1e-5,
                )
            if t == 4:  # desynchronize: env 1 starts a new episode
                h = h.at[1].set(0.0)
                c = c.at[1].set(0.0)
                hs[1] = jnp.zeros((1, hw))
                cs[1] = jnp.zeros((1, cw))

    def test_kv_cache_beyond_window_divergence_bounded(self, rng):
        """Quantify the documented beyond-window bias (families.py
        ``_act_transformer``): past ``ctx`` steps the ring-buffer keeps each
        token's K/V as ORIGINALLY computed (stale positions relative to the
        sliding window the training unroll sees), while the window oracle
        recomputes. The acting policy therefore diverges from the training
        policy for episode steps > ctx — a policy-lag-like bias absorbed by
        the IS/V-trace corrections. This test measures KL(decode || window)
        per step: ~0 while the episode fits the window, bounded (not
        unbounded drift) for a window's worth of steps beyond it."""
        from functools import partial

        from tpu_rl.models.families import _act_transformer_window

        cfg = _tf_config(act_ctx=8)
        ctx, obs_dim = cfg.effective_act_ctx, 4
        fam = build_family(cfg)
        params = fam.init_params(jax.random.key(0), seq_len=cfg.seq_len)
        act_kv = jax.jit(fam.act)
        act_win = jax.jit(
            partial(_act_transformer_window, fam.actor, ctx, obs_dim)
        )
        h_kv = jnp.zeros((1, fam.carry_widths[0]))
        c_kv = jnp.zeros((1, fam.carry_widths[1]))
        h_w = jnp.zeros((1, ctx * obs_dim))
        c_w = jnp.zeros((1, 1))

        def kl(lp, lq):  # both log-softmax, (1, A)
            p = np.exp(np.asarray(lp, np.float64))
            return float((p * (np.asarray(lp) - np.asarray(lq))).sum())

        kls = []
        for t in range(2 * ctx):
            obs = jnp.asarray(rng.normal(size=(1, obs_dim)).astype(np.float32))
            k = jax.random.key(300 + t)
            _, l1, _, h_kv, c_kv = act_kv(params, obs, h_kv, c_kv, k)
            _, l2, _, h_w, c_w = act_win(params, obs, h_w, c_w, k)
            kls.append(kl(l1, l2))
        within, beyond = kls[:ctx], kls[ctx:]
        # Inside the window: agreement to float roundoff (KL computed from
        # two f32 forward orders is noise at the 1e-7 scale, either sign).
        assert max(abs(v) for v in within) < 1e-5, within
        # Beyond the window the bias is real but must stay bounded: the same
        # order as a typical behavior-vs-target policy gap the V-trace
        # machinery is built to absorb (rho clip at ratio ~e^0.5), not a
        # runaway divergence.
        assert max(beyond) < 0.5, beyond
        print(
            f"beyond-window KL(decode||window): max={max(beyond):.4g} "
            f"mean={np.mean(beyond):.4g} (ctx={ctx}, {len(beyond)} steps)"
        )
