"""Run-history plane tests (ISSUE 20): the embedded TimeSeriesStore's
rotation / retention / crash-atomicity contracts, the ``/query``
downsampling grammar (pure and over real HTTP), the compare CLI's
verdict matrix against the committed baseline capture, the online
anomaly detector's trip conditions, the report artifacts' schema, and
autopilot signal rehydration across a restart."""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.request

import pytest

from tests.conftest import small_config
from tpu_rl.obs import (
    AnomalyDetector,
    HistoryReader,
    MetricsRegistry,
    TelemetryAggregator,
    TelemetryHTTPServer,
    TimeSeriesStore,
    channel_name,
    downsample,
    flatten_snapshots,
    history_path,
    maybe_history,
)
from tpu_rl.obs import compare, report
from tpu_rl.obs.anomaly import (
    ANOMALY_LEVEL_SHIFTS_METRIC,
    ANOMALY_SPIKES_METRIC,
)

BASELINE_DIR = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "data", "history_baseline"
)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


# ------------------------------------------------------------- flattening
def test_channel_name_drops_identity_labels_and_sorts_tail():
    assert channel_name("worker", "frame-rate") == "worker/frame-rate"
    assert channel_name(
        "worker", "frame-rate", {"pid": "7", "role": "worker", "wid": "3"}
    ) == "worker/frame-rate{wid=3}"
    assert channel_name(
        "x", "m", {"b": "2", "a": "1"}
    ) == "x/m{a=1,b=2}"


def test_flatten_snapshots_gauge_wins_counter_sums_hist_quantiles():
    snaps = [
        (
            {
                "role": "worker",
                "gauges": [["frame-rate", {"wid": "0"}, 10.0]],
                "counters": [["frames", {}, 5.0]],
                "hists": [],
            },
            0.0,
        ),
        (
            {
                "role": "worker",
                "gauges": [["frame-rate", {"wid": "0"}, 20.0]],
                "counters": [["frames", {}, 7.0]],
                # empty hist: contributes no quantile channels (explicit
                # no-data, never a fabricated zero)
                "hists": [["rtt", {}, [0] * 31, 0.0, 0]],
            },
            0.0,
        ),
    ]
    samples, kinds = flatten_snapshots(snaps)
    assert samples["worker/frame-rate{wid=0}"] == 20.0  # last write wins
    assert samples["worker/frames"] == 12.0  # counters sum across sources
    assert kinds["worker/frames"] == "counter"
    assert not any("rtt" in ch for ch in samples)

    reg = MetricsRegistry(role="learner", pid=1)
    reg.histogram("lat").observe(1.0)
    reg.histogram("lat").observe(1.0)
    samples, kinds = flatten_snapshots([(reg.snapshot(), 0.0)])
    assert "learner/lat-p50" in samples and "learner/lat-p99" in samples
    assert kinds["learner/lat-p50"] == "quantile"


def test_downsample_golden():
    pts = [(0.0, 1.0), (1.0, 3.0), (2.5, 5.0), (3.0, 7.0)]
    rows = downsample(pts, 2.0, start=0.0)
    assert rows == [
        {"t": 0.0, "n": 2, "min": 1.0, "max": 3.0, "last": 3.0, "mean": 2.0},
        {"t": 2.0, "n": 2, "min": 5.0, "max": 7.0, "last": 7.0, "mean": 6.0},
    ]
    # Bucket alignment follows `start`; the same step over a shifted start
    # yields shifted bucket edges.
    assert downsample(pts, 2.0, start=-1.0)[0]["t"] == -1.0
    assert downsample([], 2.0) == []


# ------------------------------------------------------ rotation/retention
def test_store_rotates_chunks_and_gcs_past_retention(tmp_path):
    clock = FakeClock(100.0)
    store = TimeSeriesStore(
        str(tmp_path), chunk_s=10.0, retention_s=25.0, clock=clock
    )
    for i in range(5):
        clock.t = 100.0 + 10.0 * i
        store.append({"r/x": float(i)}, kinds={"r/x": "gauge"})
    assert store.n_rotated == 4
    # t=140: horizon 115; chunks starting at 100 (covers to 110) die,
    # 110-start (covers to 120) survives.
    names = sorted(os.listdir(str(tmp_path)))
    assert "chunk-000000000100000.jsonl" not in names
    assert "chunk-000000000110000.jsonl" in names
    assert store.n_gc >= 1
    # Everything still on disk reads back in order.
    assert [v for _t, v in store.points("r/x")] == [1.0, 2.0, 3.0, 4.0]
    store.close()


def test_store_resume_inherits_series_index(tmp_path):
    clock = FakeClock(0.0)
    store = TimeSeriesStore(str(tmp_path), clock=clock)
    store.append({"r/a": 1.0}, kinds={"r/a": "gauge"})
    store.close()
    store2 = TimeSeriesStore(str(tmp_path), clock=clock)
    assert store2.series().get("r/a") == "gauge"
    store2.close()


def test_torn_tail_line_is_invisible(tmp_path):
    clock = FakeClock(0.0)
    store = TimeSeriesStore(str(tmp_path), clock=clock)
    store.append({"r/x": 1.0}, kinds={"r/x": "gauge"})
    store.append({"r/x": 2.0})
    store.close()
    chunk = next(
        p for p in tmp_path.iterdir() if p.name.startswith("chunk-")
    )
    with open(chunk, "a") as f:
        f.write('{"t": 3.0, "s": {"r/x": 99')  # crash mid-write
    reader = HistoryReader(str(tmp_path))
    assert [v for _t, v in reader.points("r/x")] == [1.0, 2.0]
    # Non-dict and unstamped rows are skipped the same way.
    with open(chunk, "a") as f:
        f.write("\n[1,2]\n{\"s\": {\"r/x\": 5}}\n")
    assert [v for _t, v in reader.points("r/x")] == [1.0, 2.0]


def test_reader_series_falls_back_to_chunk_scan(tmp_path):
    clock = FakeClock(0.0)
    store = TimeSeriesStore(str(tmp_path), clock=clock)
    store.append({"r/x": 1.0}, kinds={"r/x": "gauge"})
    store.close()
    os.remove(tmp_path / "series.json")  # index torn away by a crash
    reader = HistoryReader(str(tmp_path))
    assert reader._chunk_s_hint() is None
    assert reader.series() == {"r/x": "unknown"}
    # Without the chunk_s hint no chunk is skipped on start-bounded reads.
    assert reader.points("r/x", start=0.0) == [(0.0, 1.0)]


def test_chunk_s_hint_bounds_skip_without_single_writer_assumption(tmp_path):
    # Writer A's chunk starts at t=0 and covers rows through t=9; writer
    # B's chunk (same dir) starts at t=2. A start=8 query must still read
    # chunk A — its coverage is bounded by chunk_s, not by B's start.
    clock_a, clock_b = FakeClock(0.0), FakeClock(2.0)
    a = TimeSeriesStore(str(tmp_path), chunk_s=10.0, clock=clock_a)
    b = TimeSeriesStore(str(tmp_path), chunk_s=10.0, clock=clock_b)
    a.append({"r/a": 1.0}, kinds={"r/a": "gauge"})
    b.append({"r/b": 1.0}, kinds={"r/b": "gauge"})
    clock_a.t = 9.0
    a.append({"r/a": 2.0})
    a.close()
    b.close()
    reader = HistoryReader(str(tmp_path))
    assert reader._chunk_s_hint() == 10.0
    assert reader.points("r/a", start=8.0) == [(9.0, 2.0)]


def test_record_feeds_from_aggregator_and_publishes_own_counters(tmp_path):
    agg = TelemetryAggregator()
    agg.registry.gauge("storage-queue-depth").set(4.0)
    clock = FakeClock(50.0)
    store = TimeSeriesStore(str(tmp_path), clock=clock)
    samples = store.record(agg, extra={"signals/burn:x": 1.5})
    assert samples["storage/storage-queue-depth"] == 4.0
    assert samples["signals/burn:x"] == 1.5
    assert store.series()["signals/burn:x"] == "signal"
    assert agg.registry.counter("history-rows").value == 1.0
    assert store.points("signals/burn:x") == [(50.0, 1.5)]
    store.close()


# ---------------------------------------------------------------- gating
def test_history_path_and_maybe_history_gating(tmp_path):
    cfg = small_config()
    assert cfg.result_dir is None and history_path(cfg) is None
    assert maybe_history(cfg) is None  # telemetry plane off -> no store
    cfg = small_config(result_dir=str(tmp_path))
    assert history_path(cfg) == str(tmp_path / "history")
    store = maybe_history(cfg)
    assert isinstance(store, TimeSeriesStore)
    assert store.anomaly is not None
    store.close()
    cfg = small_config(
        result_dir=str(tmp_path), history_dir=str(tmp_path / "elsewhere")
    )
    assert history_path(cfg) == str(tmp_path / "elsewhere")


def test_config_validates_history_knobs(tmp_path):
    small_config(history_chunk_s=60.0, history_retention_s=3600.0).validate()
    with pytest.raises(AssertionError):
        small_config(history_chunk_s=0.0).validate()
    with pytest.raises(AssertionError):
        small_config(
            history_chunk_s=120.0, history_retention_s=60.0
        ).validate()


# ----------------------------------------------------------------- /query
def _query_fixture(tmp_path):
    clock = FakeClock(0.0)
    store = TimeSeriesStore(str(tmp_path), clock=clock)
    for i in range(10):
        clock.t = float(i)
        store.append(
            {"r/x": float(i), "r/y": 1.0},
            kinds={"r/x": "gauge", "r/y": "counter"},
        )
    store.close()
    return HistoryReader(str(tmp_path))


def test_http_query_contract(tmp_path):
    reader = _query_fixture(tmp_path)
    status, doc = reader.http_query({})
    assert status == 200
    assert doc["series"] == [
        {"name": "r/x", "kind": "gauge"},
        {"name": "r/y", "kind": "counter"},
    ]
    status, doc = reader.http_query({"metric": "r/x", "start": "2", "end": "4"})
    assert status == 200 and doc["n"] == 3
    assert doc["points"] == [[2.0, 2.0], [3.0, 3.0], [4.0, 4.0]]
    status, doc = reader.http_query({"metric": "r/x", "step": "5"})
    assert status == 200 and [b["n"] for b in doc["buckets"]] == [5, 5]
    assert doc["buckets"][1]["mean"] == 7.0
    status, doc = reader.http_query({"metric": "r/x", "start": "nope"})
    assert status == 400
    status, doc = reader.http_query({"metric": "r/x", "step": "-1"})
    assert status == 400
    status, doc = reader.http_query({"metric": "absent"})
    assert status == 200 and doc["n"] == 0 and doc["points"] == []


@pytest.mark.timeout(30)
def test_http_query_endpoint_end_to_end(tmp_path):
    agg = TelemetryAggregator()
    srv = TelemetryHTTPServer(agg, port=0)  # history not wired
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/query", timeout=5
            )
        assert ei.value.code == 404
    finally:
        srv.close()

    reader = _query_fixture(tmp_path)
    srv = TelemetryHTTPServer(agg, port=0, query=reader.http_query)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(f"{base}/query", timeout=5) as r:
            assert json.loads(r.read())["series"][0]["name"] == "r/x"
        with urllib.request.urlopen(
            f"{base}/query?metric=r%2Fx&start=2&end=4&step=2", timeout=5
        ) as r:
            doc = json.loads(r.read())
            assert doc["step"] == 2.0 and len(doc["buckets"]) == 2
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"{base}/query?metric=r%2Fx&start=bad", timeout=5
            )
        assert ei.value.code == 400
    finally:
        srv.close()


# ---------------------------------------------------------------- compare
def test_compare_channel_verdict_matrix():
    base = [100.0, 101.0, 99.0, 100.0, 102.0]
    up, down = "r/x-per-s", "r/frame-rtt-ms"
    assert compare.direction(up) == "up"
    assert compare.direction(down) == "down"
    assert compare.direction("r/knob") == "neutral"
    assert compare.compare_channel(base, base, up)["verdict"] == "ok"
    assert compare.compare_channel(
        base, [50.0] * 5, up
    )["verdict"] == "regressed"
    assert compare.compare_channel(
        base, [200.0] * 5, up
    )["verdict"] == "improved"
    assert compare.compare_channel(
        base, [200.0] * 5, down
    )["verdict"] == "regressed"
    assert compare.compare_channel(
        base, [200.0] * 5, "r/knob"
    )["verdict"] == "shifted"
    row = compare.compare_channel(base, None, up)
    assert row["verdict"] == "no-data"
    assert compare.compare_channel(base, [5.0], up)["verdict"] == "no-data"
    assert compare.compare_channel(None, base, up)["verdict"] == "new"
    # both-empty never gates: a run compared to itself must be green
    assert compare.compare_channel(None, None, up)["verdict"] == "skipped"
    # The relative floor keeps a quiet channel's band non-degenerate.
    quiet = [100.0] * 5
    row = compare.compare_channel(quiet, [95.0] * 5, up)
    assert row["verdict"] == "ok" and row["band"] == 10.0


def test_trim_warmup_is_time_based():
    pts = [(0.0, 1.0), (1.0, 2.0), (9.0, 3.0), (10.0, 4.0)]
    assert compare.trim_warmup(pts, 0.2) == [3.0, 4.0]
    assert compare.trim_warmup([], 0.2) == []


def test_compare_against_committed_baseline(tmp_path, capsys):
    # Self-compare of the committed capture must be green.
    assert compare.main([BASELINE_DIR, BASELINE_DIR]) == 0
    assert "OK" in capsys.readouterr().out

    # A candidate doctored 2x slower on the throughput channel gates red.
    slow = tmp_path / "slow"
    slow.mkdir()
    for fname in os.listdir(BASELINE_DIR):
        src = os.path.join(BASELINE_DIR, fname)
        if not fname.startswith("chunk-"):
            with open(slow / fname, "w") as out:
                out.write(open(src).read())
            continue
        with open(src) as f, open(slow / fname, "w") as out:
            for line in f:
                row = json.loads(line)
                ch = "learner/learner-updates-per-s"
                row["s"][ch] = row["s"][ch] * 0.5
                out.write(json.dumps(row) + "\n")
    assert compare.main([BASELINE_DIR, str(slow)]) == 1
    out = capsys.readouterr().out
    assert "regressed" in out and "learner-updates-per-s" in out

    doc = compare.compare_runs(BASELINE_DIR, str(slow))
    verdicts = {r["channel"]: r["verdict"] for r in doc["rows"]}
    assert verdicts["learner/learner-updates-per-s"] == "regressed"
    assert verdicts["learner/learner-lr"] == "ok"
    assert not doc["ok"]

    # A candidate missing a recorded channel is explicit no-data: red.
    dropped = tmp_path / "dropped"
    dropped.mkdir()
    for fname in os.listdir(BASELINE_DIR):
        src = os.path.join(BASELINE_DIR, fname)
        if not fname.startswith("chunk-"):
            with open(dropped / fname, "w") as out:
                out.write(open(src).read())
            continue
        with open(src) as f, open(dropped / fname, "w") as out:
            for line in f:
                row = json.loads(line)
                row["s"].pop("learner/learner-updates-per-s", None)
                out.write(json.dumps(row) + "\n")
    assert compare.main([BASELINE_DIR, str(dropped)]) == 1
    assert "no-data" in capsys.readouterr().out

    # Missing store entirely: exit 2 (usage error, not a verdict).
    assert compare.main([BASELINE_DIR, str(tmp_path / "nothing")]) == 2


# ---------------------------------------------------------------- anomaly
def test_anomaly_spike_trips_and_is_clamped():
    det = AnomalyDetector()
    kinds = {"r/x": "gauge"}
    for _ in range(20):
        assert det.observe({"r/x": 100.0 + 0.01}, kinds) == []
    events = det.observe({"r/x": 10_000.0}, kinds)
    assert events == [("r/x", "spike")]
    # The spike fold is clamped: the next normal sample is NOT an anomaly
    # in the other direction (mean was not dragged to 10k).
    assert det.observe({"r/x": 100.0}, kinds) == []


def test_anomaly_level_shift_needs_sustain():
    det = AnomalyDetector()
    kinds = {"r/x": "gauge"}
    for i in range(30):
        det.observe({"r/x": 100.0 + (i % 3) * 0.5}, kinds)
    # 102.5 sits between the level (3 sigma) and spike (8 sigma) bars for
    # this trace's dispersion: only a sustained streak may fire.
    fired = []
    for _ in range(10):
        fired += det.observe({"r/x": 102.5}, kinds)
    assert ("r/x", "level-shift") in fired
    assert ("r/x", "spike") not in fired
    # One stray out-of-band sample (streak broken) never fires.
    det2 = AnomalyDetector()
    for i in range(30):
        det2.observe({"r/x": 100.0 + (i % 3) * 0.5}, kinds)
    assert det2.observe({"r/x": 102.5}, kinds) == []
    assert det2.observe({"r/x": 100.0}, kinds) == []
    assert det2.observe({"r/x": 102.5}, kinds) == []


def test_anomaly_slow_drift_never_trips_and_counters_skipped():
    det = AnomalyDetector()
    kinds = {"r/x": "gauge", "r/c": "counter"}
    x = 100.0
    for i in range(500):
        x *= 1.001  # 0.1%/tick drift: the EWMA tracks it
        # counters ratchet by construction — never anomaly material
        assert det.observe({"r/x": x, "r/c": float(i * 1000)}, kinds) == []


def test_anomaly_publishes_slo_able_counters():
    det = AnomalyDetector()
    reg = MetricsRegistry(role="storage")
    kinds = {"r/x": "gauge"}
    for _ in range(20):
        det.observe({"r/x": 100.0}, kinds, registry=reg)
    det.observe({"r/x": 10_000.0}, kinds, registry=reg)
    spike = reg.counter(ANOMALY_SPIKES_METRIC, labels={"channel": "r/x"})
    assert spike.value == 1.0
    shifts = reg.counter(
        ANOMALY_LEVEL_SHIFTS_METRIC, labels={"channel": "r/x"}
    )
    assert shifts.value == 0.0


# ----------------------------------------------------------------- report
def test_report_schema_markdown_html_and_events(tmp_path):
    run = tmp_path / "run"
    run.mkdir()
    clock = FakeClock(1000.0)
    store = TimeSeriesStore(str(run / "history"), clock=clock)
    for i in range(12):
        clock.t = 1000.0 + i
        store.append(
            {
                "colocated/colocated-env-steps-per-s": 50.0 + i,
                "learner/learner-update-index": float(i),
                "r/uncharted": 1.0,
            },
            kinds={
                "colocated/colocated-env-steps-per-s": "gauge",
                "learner/learner-update-index": "gauge",
                "r/uncharted": "gauge",
            },
        )
    store.close()
    with open(run / "chaos.jsonl", "w") as f:
        f.write(json.dumps(
            {"ev": "chaos", "action": "kill", "target": "worker-0-1",
             "t": 1004.0}
        ) + "\n")
        f.write('{"torn')  # crash mid-append: skipped
    with open(run / "learner_rollback.jsonl", "w") as f:
        f.write(json.dumps({"idx": 6, "epoch": 1, "t": 1006.0}) + "\n")

    doc = report.build_report(str(run))
    assert doc["n_series"] == 3
    names = [ch["name"] for ch in doc["channels"]]
    assert names == [
        "colocated/colocated-env-steps-per-s",
        "learner/learner-update-index",
    ]  # default patterns chart the health set, not every channel
    ch = doc["channels"][0]
    for key in ("kind", "n", "t0", "t1", "mean", "min", "max", "last"):
        assert key in ch
    assert [e["kind"] for e in doc["events"]] == ["chaos", "rollback"]
    assert doc["events"][0]["label"] == "kill:worker-0-1"
    assert doc["events"][1]["label"] == "idx=6@e1"

    md = report.render_markdown(doc)
    assert "| `colocated/colocated-env-steps-per-s` |" in md
    assert "chaos" in md and "kill:worker-0-1" in md
    html_text = report.render_html(
        doc, HistoryReader(str(run / "history"))
    )
    assert "<svg" in html_text and "polyline" in html_text
    assert "chaos: kill:worker-0-1" in html_text

    assert report.main([str(run)]) == 0
    for fname in ("report.json", "report.md", "report.html"):
        assert (run / fname).is_file()
    assert json.loads((run / "report.json").read_text())["channels"]
    # No history store: explicit error exit, never an empty "healthy" doc.
    assert report.main([str(tmp_path / "empty")]) == 2


# ---------------------------------------------------- signal rehydration
def test_rehydrate_signals_restores_all_kinds_across_restart(tmp_path):
    from tpu_rl.autopilot.signals import (
        SignalStore,
        rehydrate_signals,
        signal_channels,
    )

    # First controller life: scraped signals persisted on the exporter
    # cadence as signals/<key> channels.
    mono = FakeClock(100.0)
    live = SignalStore(window_s=60.0, clock=mono)
    wall = FakeClock(5000.0)
    store = TimeSeriesStore(str(tmp_path), clock=wall)
    for i in range(5):
        mono.t = 100.0 + 10.0 * i
        wall.t = 5000.0 + 10.0 * i
        for key, v in (
            ("burn:frames", 0.1 * i),
            ("goodput:learner", 0.8),
            ("gauge:learner-mfu", 0.3),
            ("counter:anomaly-spikes", float(i)),
        ):
            live.put(key, v)
        # Mirror TimeSeriesStore.record(extra=...): signal channels are
        # indexed with kind "signal" so rehydration can discover them.
        chans = signal_channels(live)
        store.append(
            {**chans, "storage/other": 1.0},
            kinds={**{ch: "signal" for ch in chans},
                   "storage/other": "gauge"},
        )
    store.close()

    # Restart: a fresh store rehydrates every signal kind — not just the
    # burn rates the /slo replay covers.
    mono2 = FakeClock(150.0)
    fresh = SignalStore(window_s=60.0, clock=mono2)
    n = rehydrate_signals(
        fresh, HistoryReader(str(tmp_path)),
        now_wall=5045.0, now_mono=150.0,
    )
    assert n > 0
    assert fresh.latest("burn:frames") == pytest.approx(0.4)
    assert fresh.latest("goodput:learner") == 0.8
    assert fresh.latest("counter:anomaly-spikes") == 4.0
    assert "storage/other" not in fresh.snapshot()  # non-signal channels
    # Window math: only samples inside window_s of now_wall restored,
    # converted to the monotonic clock.
    ts = [t for t, _v in fresh.series("burn:frames")]
    assert all(90.0 <= t <= 150.0 for t in ts)
    # Live puts after rehydration are NOT blocked by the monotonic guard.
    mono2.t = 151.0
    fresh.put("burn:frames", 0.9)
    assert fresh.latest("burn:frames") == 0.9


def test_rehydrate_drops_future_samples(tmp_path):
    from tpu_rl.autopilot.signals import SignalStore, rehydrate_signals

    wall = FakeClock(1000.0)
    store = TimeSeriesStore(str(tmp_path), clock=wall)
    store.append({"signals/burn:x": 1.0}, kinds={"signals/burn:x": "signal"})
    wall.t = 2000.0  # cross-boot clock step: lands beyond "now"
    store.append({"signals/burn:x": 7.0})
    store.close()
    fresh = SignalStore(window_s=1e6, clock=FakeClock(50.0))
    rehydrate_signals(
        fresh, HistoryReader(str(tmp_path)), now_wall=1005.0, now_mono=50.0
    )
    assert fresh.latest("signals/burn:x".removeprefix("signals/")) == 1.0


# -------------------------------------------------------------- sparklines
def test_sparkline_and_collect_history():
    from tpu_rl.obs import top

    assert top.sparkline([]) == ""
    assert top.sparkline([5.0, 5.0]) == top.SPARK_BLOCKS[3] * 2
    ramp = top.sparkline([float(i) for i in range(8)])
    assert ramp == top.SPARK_BLOCKS
    assert len(top.sparkline(list(range(1000)))) == top._SPARK_WIDTH

    def fake_fetch_json(url, timeout=2.0):
        if url.endswith("/query"):
            return {"series": [
                {"name": "learner/learner-mfu", "kind": "gauge"},
                {"name": "worker/frame-rate{wid=1}", "kind": "gauge"},
                {"name": "storage/uninteresting", "kind": "gauge"},
            ]}
        assert "metric=learner%2Flearner-mfu" in url
        return {"points": [[1.0, 0.2], [2.0, 0.4]]}

    hist = top.collect_history("http://x", fetch_json_fn=fake_fetch_json)
    assert hist == {"learner-mfu": [0.2, 0.4]}  # labeled + unmatched skipped
    # Plane off (404 error body) -> None -> panels render blank.
    assert top.collect_history(
        "http://x", fetch_json_fn=lambda u, t=2.0: {"error": "nope"}
    ) is None
    assert top.collect_history(
        "http://x", fetch_json_fn=lambda u, t=2.0: None
    ) is None
