"""Centralized-inference subsystem tests (ISSUE 2): ROUTER/DEALER transport,
the InferenceService's dynamic batching (deadline + full flush), server-side
recurrent carry, rejected-frame tolerance, clean shutdown, the worker's
remote-acting path with its local fallback, and the stat plumbing that
surfaces ``n_model_loads`` / ``n_rejected`` (the cluster e2e remote run lives
in ``test_runtime.py::test_remote_acting_cluster_end_to_end``)."""

import threading
import time

import jax
import numpy as np
import pytest
import zmq

from tests.conftest import small_config
from tpu_rl.models.families import build_family
from tpu_rl.runtime.inference_service import InferenceClient, InferenceService
from tpu_rl.runtime.manager import Manager, STAT_WINDOW
from tpu_rl.runtime.protocol import Protocol, encode
from tpu_rl.runtime.storage import LearnerStorage, STAT_SLOTS
from tpu_rl.runtime.transport import Dealer, Router

BASE = 30150  # this module's port range; test_runtime owns 29xxx


def _svc_config(**kw):
    base = dict(
        env="CartPole-v1",
        algo="PPO",
        act_mode="remote",
        worker_num_envs=2,
        inference_batch=8,
        inference_flush_us=2000,
        inference_timeout_ms=5000,
        inference_retries=1,
        worker_step_sleep=0.0,
    )
    base.update(kw)
    return small_config(**base)


def _start_service(port: int, **cfg_kw):
    cfg = _svc_config(**cfg_kw)
    family = build_family(cfg)
    params = family.init_params(jax.random.key(0), seq_len=cfg.seq_len)
    svc = InferenceService(cfg, family, params, port=port).start()
    assert svc.wait_ready(120.0), svc.error
    assert svc.error is None, svc.error
    return cfg, family, params, svc


def _obs(n, cfg, seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((n, int(cfg.obs_shape[0]))).astype(np.float32)


# ------------------------------------------------------------- transport
class TestRouterDealer:
    def test_roundtrip(self):
        port = BASE
        router = Router("127.0.0.1", port, bind=True)
        dealer = Dealer("127.0.0.1", port, identity=b"client-a")
        try:
            payload = {"seq": 7, "obs": np.ones((2, 4), np.float32)}
            dealer.send(Protocol.ObsRequest, payload)
            got = router.recv(timeout_ms=5000)
            assert got is not None
            identity, proto, decoded = got
            assert identity == b"client-a"
            assert proto == Protocol.ObsRequest
            assert decoded["seq"] == 7
            np.testing.assert_array_equal(decoded["obs"], payload["obs"])

            router.send(identity, Protocol.Act, {"seq": 7, "act": [1.0]})
            reply = dealer.recv(timeout_ms=5000)
            assert reply is not None
            rproto, rpayload = reply
            assert rproto == Protocol.Act and rpayload["seq"] == 7
        finally:
            dealer.close()
            router.close()

    def test_replies_route_per_identity(self):
        port = BASE + 1
        router = Router("127.0.0.1", port, bind=True)
        a = Dealer("127.0.0.1", port, identity=b"a")
        b = Dealer("127.0.0.1", port, identity=b"b")
        try:
            a.send(Protocol.ObsRequest, {"seq": 1})
            b.send(Protocol.ObsRequest, {"seq": 2})
            seen = {}
            for _ in range(2):
                identity, _proto, payload = router.recv(timeout_ms=5000)
                seen[identity] = payload["seq"]
            assert seen == {b"a": 1, b"b": 2}
            # replies cross: each dealer gets exactly its own
            router.send(b"b", Protocol.Act, {"seq": 2})
            router.send(b"a", Protocol.Act, {"seq": 1})
            assert a.recv(timeout_ms=5000)[1]["seq"] == 1
            assert b.recv(timeout_ms=5000)[1]["seq"] == 2
        finally:
            a.close()
            b.close()
            router.close()

    def test_malformed_frame_counted_not_raised(self):
        port = BASE + 2
        router = Router("127.0.0.1", port, bind=True)
        ctx = zmq.Context.instance()
        raw = ctx.socket(zmq.DEALER)
        raw.connect(f"tcp://127.0.0.1:{port}")
        good = Dealer("127.0.0.1", port, identity=b"good")
        try:
            raw.send_multipart([b"\x00garbage", b"not-a-frame"])
            assert router.recv(timeout_ms=5000) is None  # dropped, counted
            assert router.n_rejected == 1
            # the fabric survives: a well-formed client still gets through
            good.send(Protocol.ObsRequest, {"seq": 3})
            got = router.recv(timeout_ms=5000)
            assert got is not None and got[2]["seq"] == 3
        finally:
            raw.close(linger=0)
            good.close()
            router.close()


# -------------------------------------------------------------- service
class TestInferenceService:
    def test_deadline_flush_partial_batch(self):
        port = BASE + 10
        cfg, family, _params, svc = _start_service(
            port, inference_batch=64, inference_flush_us=1500
        )
        client = InferenceClient(cfg, "127.0.0.1", port, wid=0)
        try:
            obs = _obs(2, cfg)
            first = np.ones(2, np.float32)
            reply = client.act(obs, first)
            # 2 rows can never fill a 64-slot batch: only the deadline can
            # have flushed, inside the client's timeout.
            assert reply is not None and reply["seq"] == 0
            assert reply["act"].shape == (2, 1)
            assert reply["logits"].shape == (2, int(cfg.action_space))
            assert reply["log_prob"].shape == (2, 1)
            assert svc.n_flush_deadline >= 1 and svc.n_flush_full == 0
        finally:
            client.close()
            svc.close()

    def test_full_batch_flushes_before_deadline(self):
        port = BASE + 11
        # batch == rows-per-request, deadline far away: the full-batch
        # trigger must fire well before the 2 s flush window.
        cfg, _family, _params, svc = _start_service(
            port, inference_batch=2, inference_flush_us=2_000_000
        )
        client = InferenceClient(cfg, "127.0.0.1", port, wid=0)
        try:
            t0 = time.perf_counter()
            reply = client.act(_obs(2, cfg), np.ones(2, np.float32))
            dt = time.perf_counter() - t0
            assert reply is not None
            assert dt < 1.5, f"full batch waited on the deadline ({dt:.2f}s)"
            assert svc.n_flush_full >= 1
        finally:
            client.close()
            svc.close()

    def test_carry_lives_server_side(self):
        """LSTM pre-step carry semantics across the wire: the first tick of
        an episode acts from (and reports) a ZERO carry; the next tick's
        reported pre-step carry equals the post-step carry a local worker
        would have computed — without the client ever shipping h/c."""
        port = BASE + 12
        cfg, family, params, svc = _start_service(port)
        assert family.store_carry
        client = InferenceClient(cfg, "127.0.0.1", port, wid=0)
        try:
            obs1, obs2 = _obs(2, cfg, seed=1), _obs(2, cfg, seed=2)
            r1 = client.act(obs1, np.ones(2, np.float32))
            assert r1 is not None
            np.testing.assert_array_equal(r1["hx"], 0.0)
            np.testing.assert_array_equal(r1["cx"], 0.0)

            # The post-step carry is a deterministic function of (params,
            # obs, pre-step carry) — sampling only affects action/log_prob —
            # so the local replay pins what the server must hold.
            import jax.numpy as jnp

            hw, cw = family.carry_widths
            _a, _lg, _lp, h2, c2 = family.act(
                params, jnp.asarray(obs1), jnp.zeros((2, hw)),
                jnp.zeros((2, cw)), jax.random.key(9),
            )
            r2 = client.act(obs2, np.zeros(2, np.float32))
            assert r2 is not None
            np.testing.assert_allclose(
                r2["hx"], np.asarray(h2), rtol=1e-5, atol=1e-6
            )
            np.testing.assert_allclose(
                r2["cx"], np.asarray(c2), rtol=1e-5, atol=1e-6
            )
        finally:
            client.close()
            svc.close()

    def test_param_swap_changes_policy(self):
        port = BASE + 13
        cfg, family, _params, svc = _start_service(port)
        client = InferenceClient(cfg, "127.0.0.1", port, wid=0)
        try:
            obs = _obs(2, cfg, seed=3)
            first = np.ones(2, np.float32)  # zero carry -> logits are
            # a deterministic function of params and obs alone
            before = client.act(obs, first)["logits"]
            again = client.act(obs, first)["logits"]
            np.testing.assert_allclose(again, before, rtol=1e-6)

            svc.set_params(
                family.init_params(jax.random.key(123), seq_len=cfg.seq_len)
            )
            after = client.act(obs, first)["logits"]
            assert not np.allclose(after, before), (
                "set_params did not change the served policy"
            )
        finally:
            client.close()
            svc.close()

    def test_rejected_request_does_not_kill_service(self):
        port = BASE + 14
        cfg, _family, _params, svc = _start_service(port)
        bad = Dealer("127.0.0.1", port, identity=b"bad")
        client = InferenceClient(cfg, "127.0.0.1", port, wid=0)
        try:
            # Decodable frame, wrong schema: dropped and counted, never
            # fatal — then a well-formed client is still served.
            bad.send(Protocol.ObsRequest, {"seq": 0})  # no obs/first
            bad.send(Protocol.Stat, 1.0)  # wrong protocol entirely
            reply = client.act(_obs(2, cfg), np.ones(2, np.float32))
            assert reply is not None
            assert svc.running and svc.error is None
            deadline = time.time() + 5
            while svc.n_rejected_payload < 2 and time.time() < deadline:
                time.sleep(0.05)
            assert svc.n_rejected_payload == 2
        finally:
            bad.close()
            client.close()
            svc.close()

    def test_clean_shutdown_releases_port(self):
        port = BASE + 15
        _cfg, _family, _params, svc = _start_service(port)
        assert svc.running
        svc.close()
        assert not svc.running and svc.error is None
        # the socket is really gone: the port can be rebound immediately
        router = Router("127.0.0.1", port, bind=True)
        router.close()


# ------------------------------------------------------ worker remote path
def _run_worker_capture(cfg, port_base, inference_port, n_frames=3,
                        timeout=120.0):
    """Run a Worker in a thread against a bound relay SUB; return
    (worker, rollout_frames, stat_frames)."""
    from tpu_rl.runtime.transport import Sub
    from tpu_rl.runtime.worker import Worker

    relay = Sub("127.0.0.1", port_base, bind=True)
    stop = threading.Event()
    w = Worker(
        cfg, worker_id=0, manager_ip="127.0.0.1", manager_port=port_base,
        learner_ip="127.0.0.1", model_port=port_base + 1, stop_event=stop,
        inference_port=inference_port,
    )
    t = threading.Thread(target=w.run, daemon=True)
    t.start()
    rollouts, stats = [], []
    deadline = time.time() + timeout
    try:
        while time.time() < deadline and len(rollouts) < n_frames:
            msg = relay.recv(timeout_ms=200)
            if msg is None:
                continue
            proto, payload = msg
            if proto == Protocol.RolloutBatch:
                rollouts.append(payload)
            elif proto == Protocol.Stat:
                stats.append(payload)
    finally:
        stop.set()
        t.join(timeout=30)
        relay.close()
    return w, rollouts, stats


ROLLOUT_KEYS = (
    "obs", "act", "rew", "logits", "log_prob", "is_fir", "hx", "cx", "id",
    "done",
    # telemetry echo (tpu_rl.obs): worker id + policy version ride every
    # tick in BOTH acting modes, so layout parity must cover them too
    "wid", "ver",
    # run-epoch echo (durability plane): storage fences out frames acted
    # under a pre-crash learner incarnation; -1 until a broadcast arrives
    "epoch",
)


def _layout_of(frame):
    return {
        k: (np.asarray(frame[k]).shape, np.asarray(frame[k]).dtype)
        for k in ROLLOUT_KEYS
        if k != "id"
    }


@pytest.mark.timeout(240)
def test_worker_remote_layout_matches_local():
    """Acceptance: remote acting publishes RolloutBatch frames bit-identical
    in LAYOUT (keys, shapes, dtypes) to local acting — manager, storage and
    the algorithms cannot tell the modes apart."""
    port = BASE + 20
    cfg, _family, _params, svc = _start_service(
        port, inference_batch=2, time_horizon=16
    )
    try:
        w_remote, remote_frames, _ = _run_worker_capture(
            cfg, BASE + 21, inference_port=port
        )
        assert remote_frames, "remote worker produced no rollouts"
        assert not w_remote.fell_back, "remote worker silently fell back"
        assert w_remote.n_remote_acts > 0
    finally:
        svc.close()

    local_cfg = small_config(
        env="CartPole-v1", algo="PPO", act_mode="local",
        worker_num_envs=2, worker_step_sleep=0.0, time_horizon=16,
    )
    _w, local_frames, _ = _run_worker_capture(
        local_cfg, BASE + 24, inference_port=None
    )
    assert local_frames, "local worker produced no rollouts"

    rf, lf = remote_frames[0], local_frames[0]
    assert set(rf.keys()) == set(lf.keys()) == set(ROLLOUT_KEYS)
    assert _layout_of(rf) == _layout_of(lf)


@pytest.mark.timeout(240)
def test_worker_remote_falls_back_to_local_on_dead_server():
    """Satellite: a worker whose requests time out retries, then PERMANENTLY
    falls back to local acting — rollouts keep flowing, nothing wedges."""
    cfg = _svc_config(
        inference_timeout_ms=100, inference_retries=1, time_horizon=16
    )
    # nothing listens on the inference port
    w, rollouts, stats = _run_worker_capture(
        cfg, BASE + 27, inference_port=BASE + 29
    )
    assert w.fell_back, "worker never fell back from the dead server"
    assert w.n_remote_acts == 0
    assert rollouts, "fallback worker stopped producing rollouts"
    # satellite: the episode Stat payload surfaces the health counters
    if stats:
        assert {"rew", "n_model_loads", "n_rejected", "wid"} <= set(
            stats[0]
        )


@pytest.mark.timeout(240)
def test_worker_stat_carries_model_loads():
    """Satellite: n_model_loads is no longer a write-only counter — it rides
    every episode Stat. With a live model publisher the count becomes
    positive; without one it reports an honest zero."""
    from tpu_rl.runtime.transport import MODEL_HWM, Pub

    cfg = small_config(
        env="CartPole-v1", algo="PPO", worker_num_envs=2,
        worker_step_sleep=0.0, time_horizon=8,
    )
    port_base = BASE + 30
    family = build_family(cfg)
    params = family.init_params(jax.random.key(1), seq_len=cfg.seq_len)
    model_pub = Pub("127.0.0.1", port_base + 1, bind=True, hwm=MODEL_HWM)
    publish_stop = threading.Event()

    def keep_publishing():
        import jax as _jax

        host = _jax.device_get(params["actor"])
        while not publish_stop.is_set():
            model_pub.send(Protocol.Model, {"actor": host})
            time.sleep(0.05)

    pub_thread = threading.Thread(target=keep_publishing, daemon=True)
    pub_thread.start()
    try:
        _w, _rollouts, stats = _run_worker_capture(
            cfg, port_base, inference_port=None, n_frames=30
        )
    finally:
        publish_stop.set()
        pub_thread.join(timeout=10)
        model_pub.close()
    assert stats, "no episode stats captured"
    assert all(isinstance(s, dict) for s in stats)
    assert any(s["n_model_loads"] > 0 for s in stats), (
        "worker drained a live model publisher but reported zero loads"
    )


# ------------------------------------------------------------ stat plumbing
class FakePub:
    def __init__(self):
        self.sent = []

    def send(self, proto, payload):
        self.sent.append((proto, payload))


class TestStatPlumbing:
    def test_manager_windows_dict_stats_and_relays_health(self):
        m = Manager(small_config(), 0, "127.0.0.1", 0)
        pub = FakePub()
        for i in range(STAT_WINDOW):
            # Default relay_mode is "raw": the manager receives wire parts
            # and decodes only Stat frames itself.
            m._ingest(
                Protocol.Stat,
                encode(Protocol.Stat, {
                    "rew": float(i),
                    "n_model_loads": 5,
                    "n_rejected": 2,
                    "wid": i % 2,
                }),
                pub,
            )
        assert len(pub.sent) == 1
        _proto, payload = pub.sent[0]
        assert payload["mean"] == np.mean(np.arange(float(STAT_WINDOW)))
        assert payload["n"] == STAT_WINDOW
        # cumulative counters are last-seen per wid, summed: 2 workers
        assert payload["model_loads"] == 10
        assert payload["rejected"] == 4  # no Sub bound -> workers only

    def test_manager_still_accepts_bare_float_stats(self):
        m = Manager(small_config(), 0, "127.0.0.1", 0)
        pub = FakePub()
        for i in range(STAT_WINDOW):
            m._ingest(Protocol.Stat, encode(Protocol.Stat, float(i)), pub)
        assert len(pub.sent) == 1
        assert pub.sent[0][1]["model_loads"] == 0

    def test_storage_mailbox_health_slots(self):
        assert STAT_SLOTS == 9
        cfg = small_config()
        sa = np.zeros(STAT_SLOTS, np.float32)
        storage = LearnerStorage(cfg, handles=None, learner_port=0,
                                 stat_array=sa)
        storage._relay_stat(
            {"mean": 7.5, "n": 50, "rejected": 3, "model_loads": 12,
             "relay_dropped": 2, "forward_bytes": 4096.0}
        )
        assert sa[0] == 50 and sa[1] == 7.5 and sa[2] == 1.0
        assert sa[3] == 3.0 and sa[4] == 12.0
        assert sa[5] == 2.0 and sa[6] == 4096.0
        # the membership/epoch slots are NOT stat relay state: a stat
        # write must never clobber a pending join request or the fence
        assert sa[7] == 0.0 and sa[8] == 0.0

    def test_storage_mailbox_tolerates_legacy_3_slot_array(self):
        cfg = small_config()
        sa = np.zeros(3, np.float32)  # pre-ISSUE-2 mailbox shape
        storage = LearnerStorage(cfg, handles=None, learner_port=0,
                                 stat_array=sa)
        storage._relay_stat({"mean": 1.0, "n": 50})
        assert sa[2] == 1.0


# ----------------------------------------------- serving fast path (ISSUE 16)
class TestBucketLadder:
    """Shape-bucketed recompile-free batching: ladder construction, smallest-
    covering dispatch, the single-bucket legacy fallback, and the warm-time
    compile guarantee (0 post-warm recompiles across a flush-size sweep)."""

    def _ladder(self, **kw):
        cfg = _svc_config(**kw)
        family = build_family(cfg)
        params = family.init_params(jax.random.key(0), seq_len=cfg.seq_len)
        return InferenceService(cfg, family, params, port=0)._bucket_ladder()

    def test_ladder_shapes(self):
        assert self._ladder(inference_batch=64) == [64]  # legacy fallback
        assert self._ladder(inference_batch=64, inference_buckets=8) == \
            [8, 16, 32, 64]
        assert self._ladder(inference_batch=64, inference_buckets=6) == \
            [8, 16, 32, 64]  # floor rounds up to a power of two
        assert self._ladder(inference_batch=64, inference_buckets=64) == [64]
        assert self._ladder(inference_batch=48, inference_buckets=8) == \
            [8, 16, 32, 48]  # top bucket is pad_rows itself, not a pow2
        # worker_num_envs can set the pad when it exceeds inference_batch
        assert self._ladder(
            inference_batch=8, worker_num_envs=32, inference_buckets=8
        ) == [8, 16, 32]

    @staticmethod
    def _wait_bucket(svc, bucket, want, timeout=5.0):
        """The flush counter increments after the reply send — poll briefly
        so the assertion does not race the service thread."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if svc.n_flush_bucket.get(bucket, 0) >= want:
                return True
            time.sleep(0.01)
        return False

    def test_dispatch_uses_smallest_covering_bucket(self):
        port = BASE + 60
        cfg, family, params, svc = _start_service(
            port, inference_batch=16, inference_buckets=4,
            inference_flush_us=200, worker_num_envs=16,
        )
        try:
            cl = InferenceClient(cfg, "127.0.0.1", port, wid=0)
            try:
                for n, want_bucket in ((3, 4), (4, 4), (5, 8), (11, 16)):
                    before = svc.n_flush_bucket.get(want_bucket, 0)
                    got = cl.act(_obs(n, cfg), np.ones(n, np.float32))
                    assert got is not None and got["act"].shape[0] == n
                    assert self._wait_bucket(svc, want_bucket, before + 1), (
                        n, want_bucket, dict(svc.n_flush_bucket)
                    )
            finally:
                cl.close()
        finally:
            svc.close()

    def test_single_bucket_fallback_counts_pad_rows(self):
        port = BASE + 61
        cfg, family, params, svc = _start_service(
            port, inference_batch=8, inference_flush_us=200
        )
        try:
            assert svc.buckets == [8]
            cl = InferenceClient(cfg, "127.0.0.1", port, wid=0)
            try:
                assert cl.act(_obs(2, cfg), np.ones(2, np.float32)) is not None
            finally:
                cl.close()
            assert TestBucketLadder._wait_bucket(svc, 8, 1), dict(
                svc.n_flush_bucket
            )
            assert set(svc.n_flush_bucket) == {8}
        finally:
            svc.close()

    def test_no_recompiles_across_bucket_sweep(self, tmp_path):
        """The PR 11 ratchet at every ladder shape: telemetry installs a
        per-bucket recompile watch; sweeping flush sizes across all bucket
        programs (and a mid-sweep param swap) must never hit XLA again."""
        port = BASE + 62
        cfg, family, params, svc = _start_service(
            port, inference_batch=16, inference_buckets=4,
            inference_flush_us=200, worker_num_envs=16,
            result_dir=str(tmp_path),
        )
        try:
            assert set(svc.perf_buckets) == {4, 8, 16}
            cl = InferenceClient(cfg, "127.0.0.1", port, wid=0)
            try:
                for n in (1, 4, 5, 9, 16, 2, 13):
                    assert cl.act(_obs(n, cfg), np.ones(n, np.float32)) \
                        is not None
                # a model-broadcast-style swap (host numpy tree, like the
                # wire decoder hands over) must land on the same programs
                host = jax.tree_util.tree_map(
                    np.asarray, jax.device_get(params["actor"])
                )
                svc.set_params({"actor": host}, version=2)
                for n in (1, 5, 9):
                    assert cl.act(_obs(n, cfg), np.ones(n, np.float32)) \
                        is not None
            finally:
                cl.close()
            assert svc.recompiles == 0, {
                b: t.recompiles for b, t in svc.perf_buckets.items()
            }
        finally:
            svc.close()


class TestQuantizedServing:
    def test_bf16_service_parity_and_footprint(self, tmp_path):
        """End-to-end through the wire: a bf16-serving service must agree
        with the f32 reference act on argmax at real margins and report the
        halved param footprint."""
        port = BASE + 63
        cfg, family, params, svc = _start_service(
            port, inference_dtype="bf16", inference_flush_us=200,
            result_dir=str(tmp_path), hidden_size=32,
        )
        try:
            assert 0 < svc.param_bytes < sum(
                np.asarray(x).nbytes
                for x in jax.tree_util.tree_leaves(params["actor"])
            )
            cl = InferenceClient(cfg, "127.0.0.1", port, wid=0)
            try:
                obs = _obs(4, cfg, seed=3)
                got = cl.act(obs, np.ones(4, np.float32))
            finally:
                cl.close()
            assert got is not None
            import jax.numpy as jnp

            hw, cw = family.carry_widths
            _a, ref_logits, _lp, _h2, _c2 = family.act(
                params, jnp.asarray(obs), jnp.zeros((4, hw)),
                jnp.zeros((4, cw)), jax.random.key(0),
            )
            np.testing.assert_allclose(
                got["logits"], np.asarray(ref_logits), atol=5e-2
            )
        finally:
            svc.close()

    def test_int8_set_params_roundtrip(self):
        """Swaps re-quantize on arrival: after a ver-keyed swap the served
        tree is int8-compressed, and stale swaps stay no-ops."""
        from tpu_rl.fleet import InferenceReplica
        from tpu_rl.models.quant import is_q8_leaf

        cfg = _svc_config(inference_dtype="int8")
        family = build_family(cfg)
        params = family.init_params(jax.random.key(0), seq_len=cfg.seq_len)
        svc = InferenceReplica(cfg, family, params, port=0, version=1)
        svc._params = svc._quantize(svc._params)
        svc.set_params(params, version=5)
        q8 = [
            leaf for leaf in jax.tree_util.tree_leaves(
                svc._params, is_leaf=is_q8_leaf
            ) if is_q8_leaf(leaf)
        ]
        assert q8, "int8 swap did not quantize"
        svc.set_params(params, version=4)  # stale: refused
        assert svc.n_stale_sets == 1 and svc.version == 5
