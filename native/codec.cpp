// tpu_rl native wire codec: LZ4 block-format compressor/decompressor.
//
// The reference's wire path is pickle + blosc2 (c-blosc2, clevel=1) —
// its only native data-plane component (/root/reference/utils/utils.py:244-249).
// This is the TPU framework's equivalent: a clean-room implementation of the
// public LZ4 block format (token / literals / 16-bit offset / match, as
// documented in the LZ4 spec), tuned like clevel=1: greedy single-probe hash
// matching, favoring speed over ratio. Built with `g++ -O3 -shared -fPIC`
// (see tpu_rl/runtime/native.py) and called through ctypes, which releases
// the GIL for the duration — compression runs concurrently with the Python
// event loop.
//
// Exported C ABI:
//   int64 tpurl_compress_bound(int64 n)                       -> worst-case dst size
//   int64 tpurl_compress(src, n, dst, cap)                    -> bytes written, <0 on error
//   int64 tpurl_decompress(src, n, dst, cap)                  -> bytes written, <0 on error
//   uint32 tpurl_crc32(src, n, seed)                          -> checksum (frame integrity)
//   int64 tpurl_validate_batch(parts, lens, nparts, n, kinds, maxp, out)
//                                                             -> header-only verdicts
//   int64 tpurl_validate_batch_crc(parts, lens, nparts, n, kinds, maxp, out)
//                                                             -> + body crc32 verdicts

#include <cstdint>
#include <cstring>

namespace {

constexpr int kHashLog = 16;
constexpr int kMinMatch = 4;
// Format guarantees: the last 5 bytes are always literals, and the last match
// must end at least 12 bytes before the block end.
constexpr int kLastLiterals = 5;
constexpr int kMfLimit = 12;
constexpr uint32_t kMaxOffset = 65535;

inline uint32_t read32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

inline uint32_t hash4(uint32_t v) {
  return (v * 2654435761u) >> (32 - kHashLog);
}

// Write a length with 15-in-nibble + 255-byte continuation encoding.
inline uint8_t* write_length(uint8_t* op, size_t len) {
  while (len >= 255) {
    *op++ = 255;
    len -= 255;
  }
  *op++ = static_cast<uint8_t>(len);
  return op;
}

}  // namespace

extern "C" {

int64_t tpurl_compress_bound(int64_t n) {
  if (n < 0) return -1;
  // LZ4_compressBound formula: worst case is all-literals plus continuation bytes.
  return n + n / 255 + 16;
}

int64_t tpurl_compress(const uint8_t* src, int64_t src_len, uint8_t* dst,
                       int64_t dst_cap) {
  if (src_len < 0 || dst_cap < tpurl_compress_bound(src_len)) return -1;
  const uint8_t* ip = src;
  const uint8_t* const iend = src + src_len;
  const uint8_t* anchor = src;  // start of pending literals
  uint8_t* op = dst;

  if (src_len >= kMfLimit) {
    const uint8_t* const match_limit = iend - kMfLimit;
    uint32_t table[1 << kHashLog];
    std::memset(table, 0, sizeof(table));
    // Positions stored +1 so 0 means empty.
    table[hash4(read32(ip))] = static_cast<uint32_t>(ip - src) + 1;
    ++ip;

    while (ip <= match_limit) {
      const uint32_t seq = read32(ip);
      const uint32_t h = hash4(seq);
      const uint32_t cand = table[h];
      table[h] = static_cast<uint32_t>(ip - src) + 1;
      const uint8_t* match = cand ? src + cand - 1 : nullptr;
      if (!match || static_cast<uint32_t>(ip - match) > kMaxOffset ||
          read32(match) != seq) {
        ++ip;
        continue;
      }
      // Extend the match forward (stop kLastLiterals before the end).
      const uint8_t* const mend_limit = iend - kLastLiterals;
      const uint8_t* mip = ip + kMinMatch;
      const uint8_t* mmatch = match + kMinMatch;
      while (mip < mend_limit && *mip == *mmatch) {
        ++mip;
        ++mmatch;
      }
      const size_t match_len = static_cast<size_t>(mip - ip) - kMinMatch;
      const size_t lit_len = static_cast<size_t>(ip - anchor);

      // Token.
      uint8_t* const token = op++;
      *token = 0;
      if (lit_len >= 15) {
        *token = 15 << 4;
        op = write_length(op, lit_len - 15);
      } else {
        *token = static_cast<uint8_t>(lit_len << 4);
      }
      std::memcpy(op, anchor, lit_len);
      op += lit_len;

      const uint16_t offset = static_cast<uint16_t>(ip - match);
      std::memcpy(op, &offset, 2);
      op += 2;
      if (match_len >= 15) {
        *token |= 15;
        op = write_length(op, match_len - 15);
      } else {
        *token |= static_cast<uint8_t>(match_len);
      }

      ip = mip;
      anchor = ip;
      if (ip <= match_limit) {
        table[hash4(read32(ip - 2))] = static_cast<uint32_t>(ip - 2 - src) + 1;
      }
    }
  }

  // Trailing literals.
  const size_t lit_len = static_cast<size_t>(iend - anchor);
  uint8_t* const token = op++;
  if (lit_len >= 15) {
    *token = 15 << 4;
    op = write_length(op, lit_len - 15);
  } else {
    *token = static_cast<uint8_t>(lit_len << 4);
  }
  std::memcpy(op, anchor, lit_len);
  op += lit_len;
  return op - dst;
}

int64_t tpurl_decompress(const uint8_t* src, int64_t src_len, uint8_t* dst,
                         int64_t dst_cap) {
  const uint8_t* ip = src;
  const uint8_t* const iend = src + src_len;
  uint8_t* op = dst;
  uint8_t* const oend = dst + dst_cap;
  if (src_len <= 0) return src_len == 0 ? 0 : -1;

  while (ip < iend) {
    const uint8_t token = *ip++;
    // Literals.
    size_t lit_len = token >> 4;
    if (lit_len == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return -2;
        b = *ip++;
        lit_len += b;
      } while (b == 255);
    }
    if (ip + lit_len > iend || op + lit_len > oend) return -2;
    std::memcpy(op, ip, lit_len);
    ip += lit_len;
    op += lit_len;
    if (ip >= iend) break;  // last sequence carries no match

    // Match.
    if (ip + 2 > iend) return -2;
    uint16_t offset;
    std::memcpy(&offset, ip, 2);
    ip += 2;
    if (offset == 0 || offset > op - dst) return -3;  // corrupt offset
    size_t match_len = token & 15;
    if (match_len == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return -2;
        b = *ip++;
        match_len += b;
      } while (b == 255);
    }
    match_len += kMinMatch;
    if (op + match_len > oend) return -2;
    // Overlapping copy must be byte-wise (offset may be < match_len).
    const uint8_t* match = op - offset;
    for (size_t i = 0; i < match_len; ++i) op[i] = match[i];
    op += match_len;
  }
  return op - dst;
}

uint32_t tpurl_crc32(const uint8_t* src, int64_t n, uint32_t seed) {
  // Standard CRC-32 (IEEE 802.3), reflected polynomial, slice-by-4 table
  // lookup. The batch validator CRCs every frame body of a drained deque in
  // one call, so this runs over whole rollout payloads, not just headers —
  // the earlier bitwise loop (8 shifts per byte) would have made the native
  // batch path slower than Python's zlib.crc32.
  static uint32_t table[4][256];
  static bool init = false;
  if (!init) {  // idempotent: concurrent first calls compute identical rows
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c >> 1) ^ (0xEDB88320u & (~(c & 1) + 1));
      table[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; ++i) {
      table[1][i] = (table[0][i] >> 8) ^ table[0][table[0][i] & 0xFF];
      table[2][i] = (table[1][i] >> 8) ^ table[0][table[1][i] & 0xFF];
      table[3][i] = (table[2][i] >> 8) ^ table[0][table[2][i] & 0xFF];
    }
    init = true;
  }
  uint32_t crc = ~seed;
  int64_t i = 0;
  for (; i + 4 <= n; i += 4) {
    uint32_t v;
    std::memcpy(&v, src + i, 4);
    crc ^= v;
    crc = table[3][crc & 0xFF] ^ table[2][(crc >> 8) & 0xFF] ^
          table[1][(crc >> 16) & 0xFF] ^ table[0][crc >> 24];
  }
  for (; i < n; ++i) crc = (crc >> 8) ^ table[0][(crc ^ src[i]) & 0xFF];
  return ~crc;
}

// ------------------------------------------------------------ batch validate
// Wire-protocol constants mirrored from tpu_rl/runtime/protocol.py; the
// native-vs-Python rejection-parity test pins the two implementations to the
// same verdict on every malformed-frame class.
namespace {

constexpr uint16_t kFrameMagic = 0x5452;    // "TR"
constexpr uint8_t kFrameVersion = 1;
constexpr int kHeaderSize = 12;             // <HBBII
constexpr uint32_t kMaxRaw = 1u << 30;      // declared-raw-size cap
constexpr uint8_t kCodecRaw = 0, kCodecLz4 = 1, kCodecZlib = 2;
constexpr uint16_t kTrailerMagic = 0x5443;  // "TC"
constexpr uint8_t kTrailerVersion = 1;
constexpr int kTrailerSize = 28;            // <HBxiIQq

// Per-frame verdict codes (0 = valid). The Python binding only needs the
// zero/nonzero split; distinct codes keep rejects debuggable from the bitmap.
enum Verdict : uint8_t {
  kOk = 0,
  kBadParts = 1,      // part count not 2/3 or proto part not 1 byte
  kBadProto = 2,      // unknown protocol byte
  kShortFrame = 3,    // body shorter than the header
  kBadMagic = 4,      // header magic/version mismatch
  kOversized = 5,     // declared raw size past the cap
  kRawSizeMismatch = 6,  // codec=RAW body size != declared raw size
  kBadCodec = 7,      // unknown codec id
  kBadTrailer = 8,    // trailer size/magic/version or disallowed kind
  kBadCrc = 9,        // body crc32 mismatch (crc variant only)
};

// Validate one multipart frame: the exact check set of protocol.peek (and,
// with check_crc, the pre-decompress checks of protocol.decode).
inline uint8_t validate_frame(const uint8_t* const* parts, const int64_t* lens,
                              int32_t np, bool check_crc, uint32_t trace_kinds,
                              uint8_t max_proto) {
  if ((np != 2 && np != 3) || lens[0] != 1) return kBadParts;
  const uint8_t proto = parts[0][0];
  if (proto > max_proto) return kBadProto;
  const uint8_t* frame = parts[1];
  const int64_t frame_len = lens[1];
  if (frame_len < kHeaderSize) return kShortFrame;
  uint16_t magic;
  uint32_t raw_size, crc;
  std::memcpy(&magic, frame, 2);
  const uint8_t version = frame[2], codec = frame[3];
  std::memcpy(&raw_size, frame + 4, 4);
  std::memcpy(&crc, frame + 8, 4);
  if (magic != kFrameMagic || version != kFrameVersion) return kBadMagic;
  if (raw_size > kMaxRaw) return kOversized;
  if (codec == kCodecRaw) {
    if (frame_len - kHeaderSize != static_cast<int64_t>(raw_size))
      return kRawSizeMismatch;
  } else if (codec != kCodecLz4 && codec != kCodecZlib) {
    return kBadCodec;
  }
  if (np == 3) {
    if (!(trace_kinds & (1u << proto))) return kBadTrailer;
    if (lens[2] != kTrailerSize) return kBadTrailer;
    const uint8_t* tr = parts[2];
    uint16_t tmagic;
    std::memcpy(&tmagic, tr, 2);
    if (tmagic != kTrailerMagic || tr[2] != kTrailerVersion)
      return kBadTrailer;
  }
  if (check_crc &&
      tpurl_crc32(frame + kHeaderSize, frame_len - kHeaderSize, 0) != crc)
    return kBadCrc;
  return kOk;
}

inline int64_t validate_batch_impl(const uint8_t* const* parts,
                                   const int64_t* lens, const int32_t* nparts,
                                   int64_t n_frames, bool check_crc,
                                   uint32_t trace_kinds, uint8_t max_proto,
                                   uint8_t* out) {
  if (n_frames < 0 || !parts || !lens || !nparts || !out) return -1;
  int64_t n_ok = 0, cursor = 0;
  for (int64_t i = 0; i < n_frames; ++i) {
    const int32_t np = nparts[i];
    if (np <= 0 || np > 16) {
      // Malformed packing, not a wire condition. The Python binding does not
      // flatten such frames' parts, so the cursor must not advance here.
      out[i] = kBadParts;
      continue;
    }
    out[i] = validate_frame(parts + cursor, lens + cursor, np, check_crc,
                            trace_kinds, max_proto);
    if (out[i] == kOk) ++n_ok;
    cursor += np;
  }
  return n_ok;
}

}  // namespace

// Relay-grade batch validation (protocol.peek for N frames in one GIL-free
// call): `parts`/`lens` are the flattened per-part pointers/lengths of
// n_frames multipart frames, `nparts[i]` the part count of frame i.
// `trace_kinds` is the bitmask of protocol bytes allowed to carry a trace
// trailer; `max_proto` the highest known protocol byte (both passed from
// Python so the enum there stays the single source of truth). Writes one
// verdict byte per frame (0 = forward, else reject); returns the number of
// valid frames, or -1 on malformed arguments.
int64_t tpurl_validate_batch(const uint8_t* const* parts, const int64_t* lens,
                             const int32_t* nparts, int64_t n_frames,
                             uint32_t trace_kinds, uint8_t max_proto,
                             uint8_t* out) {
  return validate_batch_impl(parts, lens, nparts, n_frames, false,
                             trace_kinds, max_proto, out);
}

// Storage-edge variant: everything tpurl_validate_batch checks PLUS the body
// crc32 against the header field — the full pre-decompress validation of
// protocol.decode, batched.
int64_t tpurl_validate_batch_crc(const uint8_t* const* parts,
                                 const int64_t* lens, const int32_t* nparts,
                                 int64_t n_frames, uint32_t trace_kinds,
                                 uint8_t max_proto, uint8_t* out) {
  return validate_batch_impl(parts, lens, nparts, n_frames, true,
                             trace_kinds, max_proto, out);
}

}  // extern "C"
