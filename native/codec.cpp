// tpu_rl native wire codec: LZ4 block-format compressor/decompressor.
//
// The reference's wire path is pickle + blosc2 (c-blosc2, clevel=1) —
// its only native data-plane component (/root/reference/utils/utils.py:244-249).
// This is the TPU framework's equivalent: a clean-room implementation of the
// public LZ4 block format (token / literals / 16-bit offset / match, as
// documented in the LZ4 spec), tuned like clevel=1: greedy single-probe hash
// matching, favoring speed over ratio. Built with `g++ -O3 -shared -fPIC`
// (see tpu_rl/runtime/native.py) and called through ctypes, which releases
// the GIL for the duration — compression runs concurrently with the Python
// event loop.
//
// Exported C ABI:
//   int64 tpurl_compress_bound(int64 n)                       -> worst-case dst size
//   int64 tpurl_compress(src, n, dst, cap)                    -> bytes written, <0 on error
//   int64 tpurl_decompress(src, n, dst, cap)                  -> bytes written, <0 on error
//   uint32 tpurl_crc32(src, n, seed)                          -> checksum (frame integrity)

#include <cstdint>
#include <cstring>

namespace {

constexpr int kHashLog = 16;
constexpr int kMinMatch = 4;
// Format guarantees: the last 5 bytes are always literals, and the last match
// must end at least 12 bytes before the block end.
constexpr int kLastLiterals = 5;
constexpr int kMfLimit = 12;
constexpr uint32_t kMaxOffset = 65535;

inline uint32_t read32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

inline uint32_t hash4(uint32_t v) {
  return (v * 2654435761u) >> (32 - kHashLog);
}

// Write a length with 15-in-nibble + 255-byte continuation encoding.
inline uint8_t* write_length(uint8_t* op, size_t len) {
  while (len >= 255) {
    *op++ = 255;
    len -= 255;
  }
  *op++ = static_cast<uint8_t>(len);
  return op;
}

}  // namespace

extern "C" {

int64_t tpurl_compress_bound(int64_t n) {
  if (n < 0) return -1;
  // LZ4_compressBound formula: worst case is all-literals plus continuation bytes.
  return n + n / 255 + 16;
}

int64_t tpurl_compress(const uint8_t* src, int64_t src_len, uint8_t* dst,
                       int64_t dst_cap) {
  if (src_len < 0 || dst_cap < tpurl_compress_bound(src_len)) return -1;
  const uint8_t* ip = src;
  const uint8_t* const iend = src + src_len;
  const uint8_t* anchor = src;  // start of pending literals
  uint8_t* op = dst;

  if (src_len >= kMfLimit) {
    const uint8_t* const match_limit = iend - kMfLimit;
    uint32_t table[1 << kHashLog];
    std::memset(table, 0, sizeof(table));
    // Positions stored +1 so 0 means empty.
    table[hash4(read32(ip))] = static_cast<uint32_t>(ip - src) + 1;
    ++ip;

    while (ip <= match_limit) {
      const uint32_t seq = read32(ip);
      const uint32_t h = hash4(seq);
      const uint32_t cand = table[h];
      table[h] = static_cast<uint32_t>(ip - src) + 1;
      const uint8_t* match = cand ? src + cand - 1 : nullptr;
      if (!match || static_cast<uint32_t>(ip - match) > kMaxOffset ||
          read32(match) != seq) {
        ++ip;
        continue;
      }
      // Extend the match forward (stop kLastLiterals before the end).
      const uint8_t* const mend_limit = iend - kLastLiterals;
      const uint8_t* mip = ip + kMinMatch;
      const uint8_t* mmatch = match + kMinMatch;
      while (mip < mend_limit && *mip == *mmatch) {
        ++mip;
        ++mmatch;
      }
      const size_t match_len = static_cast<size_t>(mip - ip) - kMinMatch;
      const size_t lit_len = static_cast<size_t>(ip - anchor);

      // Token.
      uint8_t* const token = op++;
      *token = 0;
      if (lit_len >= 15) {
        *token = 15 << 4;
        op = write_length(op, lit_len - 15);
      } else {
        *token = static_cast<uint8_t>(lit_len << 4);
      }
      std::memcpy(op, anchor, lit_len);
      op += lit_len;

      const uint16_t offset = static_cast<uint16_t>(ip - match);
      std::memcpy(op, &offset, 2);
      op += 2;
      if (match_len >= 15) {
        *token |= 15;
        op = write_length(op, match_len - 15);
      } else {
        *token |= static_cast<uint8_t>(match_len);
      }

      ip = mip;
      anchor = ip;
      if (ip <= match_limit) {
        table[hash4(read32(ip - 2))] = static_cast<uint32_t>(ip - 2 - src) + 1;
      }
    }
  }

  // Trailing literals.
  const size_t lit_len = static_cast<size_t>(iend - anchor);
  uint8_t* const token = op++;
  if (lit_len >= 15) {
    *token = 15 << 4;
    op = write_length(op, lit_len - 15);
  } else {
    *token = static_cast<uint8_t>(lit_len << 4);
  }
  std::memcpy(op, anchor, lit_len);
  op += lit_len;
  return op - dst;
}

int64_t tpurl_decompress(const uint8_t* src, int64_t src_len, uint8_t* dst,
                         int64_t dst_cap) {
  const uint8_t* ip = src;
  const uint8_t* const iend = src + src_len;
  uint8_t* op = dst;
  uint8_t* const oend = dst + dst_cap;
  if (src_len <= 0) return src_len == 0 ? 0 : -1;

  while (ip < iend) {
    const uint8_t token = *ip++;
    // Literals.
    size_t lit_len = token >> 4;
    if (lit_len == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return -2;
        b = *ip++;
        lit_len += b;
      } while (b == 255);
    }
    if (ip + lit_len > iend || op + lit_len > oend) return -2;
    std::memcpy(op, ip, lit_len);
    ip += lit_len;
    op += lit_len;
    if (ip >= iend) break;  // last sequence carries no match

    // Match.
    if (ip + 2 > iend) return -2;
    uint16_t offset;
    std::memcpy(&offset, ip, 2);
    ip += 2;
    if (offset == 0 || offset > op - dst) return -3;  // corrupt offset
    size_t match_len = token & 15;
    if (match_len == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return -2;
        b = *ip++;
        match_len += b;
      } while (b == 255);
    }
    match_len += kMinMatch;
    if (op + match_len > oend) return -2;
    // Overlapping copy must be byte-wise (offset may be < match_len).
    const uint8_t* match = op - offset;
    for (size_t i = 0; i < match_len; ++i) op[i] = match[i];
    op += match_len;
  }
  return op - dst;
}

uint32_t tpurl_crc32(const uint8_t* src, int64_t n, uint32_t seed) {
  // Standard CRC-32 (IEEE 802.3), bitwise-free table-less slice-by-1 with the
  // reflected polynomial; fast enough for frame headers and small payloads.
  uint32_t crc = ~seed;
  for (int64_t i = 0; i < n; ++i) {
    crc ^= src[i];
    for (int k = 0; k < 8; ++k) crc = (crc >> 1) ^ (0xEDB88320u & (~(crc & 1) + 1));
  }
  return ~crc;
}

}  // extern "C"
