# Developer/CI entry points. `make ci` is what the workflow runs.

PY ?= python

.PHONY: lint format-check test ci

lint:
	ruff check .

format-check:
	ruff format --check .

# Tier-1 suite: the fast CPU gate (slow-marked cluster/e2e tests excluded).
test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider

ci: lint test
