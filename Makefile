# Developer/CI entry points. `make ci` is what the workflow runs.

PY ?= python

.PHONY: lint format-check analyze typecheck test native-build protocol-matrix \
	relay-smoke diag-smoke obs-smoke trace-smoke chaos-smoke colocated-smoke \
	resume-smoke slo-smoke loadgen-smoke serving-smoke heal-smoke \
	pbt-smoke goodput-smoke autopilot-smoke sebulba-smoke history-smoke ci

lint:
	ruff check .

format-check:
	ruff format --check .

# Repo-native static analysis plane (tools/analysis): hot-path purity,
# jit-boundary hygiene, protocol/mailbox consistency, metric/config drift,
# thread discipline. Exit 0 = clean (waivers live in tools/analysis/baseline.toml).
analyze:
	$(PY) -m tools.analysis

# mypy --strict over the protocol-critical core (wire format, mailbox, shm
# rings). Skips gracefully where mypy isn't installed — CI always runs it.
typecheck:
	@if $(PY) -c "import mypy" >/dev/null 2>&1; then \
		$(PY) -m mypy tpu_rl/runtime/protocol.py tpu_rl/runtime/mailbox.py \
			tpu_rl/runtime/transport.py; \
	else \
		echo "mypy not installed; skipping typecheck (CI runs it)"; \
	fi

# Tier-1 suite: the fast CPU gate (slow-marked cluster/e2e tests excluded).
test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider

# Build (and cache) the native codec from source, then prove it loaded —
# CI must never silently fall back to the zlib/Python path.
native-build:
	JAX_PLATFORMS=cpu $(PY) -c "from tpu_rl.runtime import native; \
		assert native.available(), 'native codec failed to build'; \
		print('native codec OK:', native.LIB._name)"

# Wire-protocol + relay + chaos suites twice: once with the native codec
# force-disabled (TPU_RL_NATIVE=0 exercises the pure-Python fallback every
# deployment without a toolchain runs) and once against the freshly built
# library — both paths must hold the same contracts.
protocol-matrix: native-build
	JAX_PLATFORMS=cpu TPU_RL_NATIVE=0 $(PY) -m pytest -q \
		tests/test_protocol.py tests/test_relay_raw.py \
		tests/test_relay_units.py tests/test_native_validate.py \
		tests/test_shm_transport.py tests/test_chaos.py \
		-p no:cacheprovider
	JAX_PLATFORMS=cpu $(PY) -m pytest -q \
		tests/test_protocol.py tests/test_relay_raw.py \
		tests/test_relay_units.py tests/test_native_validate.py \
		tests/test_shm_transport.py tests/test_chaos.py \
		-p no:cacheprovider

# Fan-in A/B smoke: short raw-vs-decode run through the real Manager +
# LearnerStorage. Asserts direction only (raw >= decode frames/s) — never a
# committed number, so CI load can't make it flap. Full capture:
# TPU_RL_BENCH_RELAY=1 python bench.py  (writes bench_relay[.cpu].json).
relay-smoke:
	JAX_PLATFORMS=cpu TPU_RL_BENCH_RELAY=1 TPU_RL_BENCH_RELAY_LIGHT=1 \
		$(PY) bench.py > /dev/null

# Learning-dynamics plane smoke: the chained train step with learn_diag on
# vs off at a tiny budget. Asserts sanity only (no catastrophic overhead —
# a host sync sneaking into the step reads as 2x, not 2%) — never the
# committed <=2% number, so CI load can't make it flap. Full capture:
# TPU_RL_BENCH_DIAG=1 python bench.py  (writes bench_diag[.cpu].json).
diag-smoke:
	JAX_PLATFORMS=cpu TPU_RL_BENCH_DIAG=1 TPU_RL_BENCH_DIAG_LIGHT=1 \
		$(PY) bench.py > /dev/null

# Telemetry-plane smoke: boot the smallest real cluster with the plane on,
# scrape /metrics + /healthz mid-run, validate telemetry.json + trace.json.
obs-smoke:
	JAX_PLATFORMS=cpu PYTHONPATH=. $(PY) examples/obs_smoke.py

# Distributed-tracing smoke: cluster run with rollout lineage sampling on,
# then validate the merged fleet_trace.json — all four roles on one
# clock-corrected timeline, >=1 worker->manager->storage->learner flow chain.
trace-smoke:
	JAX_PLATFORMS=cpu PYTHONPATH=. $(PY) examples/trace_smoke.py

# Chaos smoke: run the cluster under a deterministic fault plan (worker
# kill + rollout corruption + relay delay) and assert the run completes,
# >=1 supervised restart happened, and injected corruptions == fleet
# rejected frames (exact fault accounting).
chaos-smoke:
	JAX_PLATFORMS=cpu PYTHONPATH=. $(PY) examples/chaos_smoke.py

# Colocated (Anakin) smoke: a short fused on-device CartPole run must learn
# (best-window mean return over the bar) and the colocated-vs-distributed
# bench row must emit with direction-consistent numbers. Full capture:
# TPU_RL_BENCH_COLOCATED=1 python bench.py  (writes bench_colocated[.cpu].json).
colocated-smoke:
	JAX_PLATFORMS=cpu PYTHONPATH=. $(PY) examples/colocated_smoke.py

# Resume smoke: SIGKILL the learner and storage after the first committed
# checkpoint and assert supervised respawn, monotonic resume from the newest
# committed index at a bumped run epoch, stale-epoch frames fenced, workers
# re-registered, fault accounting intact, and a planted torn save never
# restored.
resume-smoke:
	JAX_PLATFORMS=cpu PYTHONPATH=. $(PY) examples/resume_smoke.py

# SLO-plane smoke: the same small cluster twice under Config.slo_spec — a
# meetable six-rule spec (system health + learner-diag training health)
# must scrape green on /slo and exit 0; adding an impossible rule with
# slo_fail_run armed must scrape 503 and exit nonzero.
slo-smoke:
	JAX_PLATFORMS=cpu PYTHONPATH=. $(PY) examples/slo_smoke.py

# Load-plane smoke: a real two-replica inference fleet under a >=10k-client
# open-loop sweep with a SIGKILL of replica 1 mid-sweep — asserts >=99.9%
# success via hedged failover, a green sub-saturation p99:inference-rtt
# verdict, and a monotonic version floor (curve at <tmp>/loadgen.json).
loadgen-smoke:
	JAX_PLATFORMS=cpu PYTHONPATH=. $(PY) examples/loadgen_smoke.py

# Serving fast-path smoke: a two-replica fleet serving bf16-quantized
# params through the bucket ladder [8, 16] — mixed-width sweep with zero
# client failures, live replica counters holding inference-xla-recompiles
# at exactly 0 post-warm, and a live parity spot-check of the quantized
# reply logits against the local f32 reference act.
serving-smoke:
	JAX_PLATFORMS=cpu PYTHONPATH=. $(PY) examples/serving_smoke.py

# Self-healing smoke: in-jit guard bit-identity + NaN containment, then a
# NaN/spike data-chaos cluster run — >=1 watchdog rollback to a committed
# checkpoint with an epoch fence, the poisoned worker quarantined and later
# cleared, exact injected==poisoned accounting — then a clean run where the
# armed healing plane changes nothing.
heal-smoke:
	JAX_PLATFORMS=cpu PYTHONPATH=. $(PY) examples/heal_smoke.py

# Population smoke: K=4 colocated CartPole variants under the PBT
# controller, one poisoned (lr ~100x) — assert the poisoned variant is
# truncation-replaced (winner checkpoint adopted + hyperparameters
# mutated), a SIGKILL mid-exploit leaves the member resumable, and the
# final leaderboard's best fitness clears the CartPole bar.
pbt-smoke:
	JAX_PLATFORMS=cpu PYTHONPATH=. $(PY) examples/pbt_smoke.py

# Goodput-plane smoke: 3-worker cluster with the wall-clock ledger on —
# every role's bucket ratios sum to 1 within 1% (overcommit <= 1%), all
# roles show nonzero goodput, gauge:learner-goodput-ratio>0.0 evaluates
# green on /slo, a SIGSTOP'd worker surfaces as the top straggler on
# /goodput, and `python -m tpu_rl.obs.top --once` renders a live frame.
goodput-smoke:
	JAX_PLATFORMS=cpu PYTHONPATH=. $(PY) examples/goodput_smoke.py

autopilot-smoke:
	JAX_PLATFORMS=cpu PYTHONPATH=. $(PY) examples/autopilot_smoke.py

# Pod-scale colocated smoke (ISSUE 18): 2 virtual hosts train the fused
# pod-Anakin CartPole to the learning bar with a SIGKILL + rejoin (epoch
# bump, newest-committed resume, final checkpoint readable), then the
# sebulba split proves actor/learner overlap through the bounded queue.
sebulba-smoke:
	JAX_PLATFORMS=cpu PYTHONPATH=. $(PY) examples/sebulba_smoke.py

# Run-history smoke (ISSUE 20): chaos-kill cluster run with the history
# plane on — /query shows run progress, the report renders the chaos
# event overlay, self-compare is green, and doctored candidates (dropped
# channel / 20x slower) gate red. Includes the light history-overhead
# bench (zero-alloc plane-off hot path; full capture:
# TPU_RL_BENCH_HISTORY=1 python bench.py -> bench_history[.cpu].json).
history-smoke:
	JAX_PLATFORMS=cpu TPU_RL_BENCH_HISTORY=1 TPU_RL_BENCH_HISTORY_LIGHT=1 \
		$(PY) bench.py > /dev/null
	JAX_PLATFORMS=cpu PYTHONPATH=. $(PY) examples/history_smoke.py

ci: lint analyze typecheck test protocol-matrix relay-smoke diag-smoke obs-smoke \
	trace-smoke chaos-smoke colocated-smoke resume-smoke slo-smoke \
	loadgen-smoke serving-smoke heal-smoke pbt-smoke goodput-smoke \
	autopilot-smoke sebulba-smoke history-smoke
